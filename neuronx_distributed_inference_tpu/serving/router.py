"""Frontend of the scale-out split: prefix-affinity request router.

:class:`PrefixAffinityRouter` owns the arrival queue and places each request
on an :class:`~.engine.EngineReplica`:

- **Prefix-cache affinity**: the router hashes the prompt's leading full
  blocks with the SAME chained content hash the replicas' prefix caches key
  blocks by (engine.prompt_block_hashes), and scores each replica by how many
  leading blocks it already holds (device cache, idle pool, or host-RAM
  tier). The replica holding the longest prefix wins ties — the placement
  that converts block residency into skipped prefill.
- **Load balancing**: among equal-affinity replicas the one with the most KV
  headroom wins, then the shallowest queue — the admission signals
  EngineReplica.admission() exports (the same numbers the SLO monitor and a
  scrape see).
- **Graceful spill**: when the affinity target is saturated
  (``has_headroom`` false), the request places on the best-by-load admitting
  replica instead and the LOST prefix hit is recorded
  (``router_affinity_spills_total`` + lost-block count) — saturation trades
  recompute for latency, visibly.
- **Drain**: ``drain_replica(id)`` evicts the replica's live requests
  through the runner's existing mid-prompt preemption/resume path and
  re-places them (``submit(resume_tokens=...)`` on the target), preserving
  every request's emitted stream exactly across the migration.

The router is synchronous-cooperative: ``step()`` places what the replicas
can admit, then steps every replica with work (one serving wave). An async
server loop wraps ``submit``/``step``; the placement policy has no timing
dependence, so the tests drive it deterministically.

Fault tolerance (ISSUE-11): ``step()`` SUPERVISES the replicas instead of
dying with them. Each replica moves through a small lifecycle::

    HEALTHY ──exception/stall──► DEGRADED ──streak > max_retries──► FAILED
       ▲            │(bounded exponential backoff, then retried)        │
       │            └──successful step──► HEALTHY                       │
       └──────── reactivate_replica (fresh runner after FAILED) ◄───────┘

- Transient errors retry with bounded exponential backoff (``max_retries``
  consecutive failures, counted in
  ``router_replica_failures_total{replica=,reason=}`` — never silent).
- A watchdog declares a replica FAILED on repeated failure or wall-clock
  stall: the wall time of ``rep.step()`` at the router IS the router-level
  dispatch gap (the same signal PR 7's per-dispatch gap attribution
  measures inside the runner), so a wedged dispatch trips
  ``watchdog_stall_s`` without any cooperation from the wedged replica.
- Hard death (:class:`~.faults.InjectedReplicaDeath`, or any exception from
  a replica already FAILED) short-circuits to FAILED.
- The transition to FAILED dumps an automatic flight-recorder debug bundle
  (``debug_bundle_dir``) and, with ``auto_recover=True``, immediately runs
  :meth:`recover_replica` so the displaced streams continue on the
  survivors.

``recover_replica`` is the NON-cooperative counterpart of
``drain_replica``: it never touches the dead runner's device state — every
in-flight stream is rebuilt from the router's own journal (the prompt plus
every committed token in ``RouterRequest.generated``) and re-queued at the
front for ``submit(resume_tokens=...)`` on a survivor, so greedy streams
continue bit-identically (the guarantee drain/migration already meets, now
without the dead replica's help).
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import metrics as metrics_lib
from .engine import EngineReplica, prompt_block_hashes
from .faults import InjectedReplicaDeath

logger = logging.getLogger("tpu-inference")

__all__ = ["PrefixAffinityRouter", "RouterRequest", "RouterOverloaded",
           "REPLICA_HEALTHY", "REPLICA_DEGRADED", "REPLICA_FAILED",
           "REPLICA_RETIRED"]

# replica lifecycle states (serving_replica_state gauge values)
REPLICA_HEALTHY = "healthy"
REPLICA_DEGRADED = "degraded"
REPLICA_FAILED = "failed"
REPLICA_RETIRED = "retired"          # removed by remove_replica (autoscaler)
_STATE_GAUGE = {REPLICA_HEALTHY: 0, REPLICA_DEGRADED: 1, REPLICA_FAILED: 2,
                REPLICA_RETIRED: 3}


class RouterOverloaded(RuntimeError):
    """submit() shed the request — the caller should back off / 503.

    Raised by the legacy global queue bound (queue past ``shed_queue_depth``
    while the SLO signal says unhealthy) AND by the SLA brown-out ladder
    (the request's class is shed at the current degradation level).
    ``sla_class`` names the shed class (None on a classless router);
    ``retry_after_s`` is the back-off hint the caller should surface as
    Retry-After."""

    def __init__(self, msg: str, sla_class: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.sla_class = sla_class
        self.retry_after_s = retry_after_s


@dataclass
class RouterRequest:
    """Frontend-side request record: the prompt + serving params, the
    precomputed affinity hash chain, and the placement/emission state the
    router tracks across replicas (a request may migrate)."""

    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    sampling_params: Optional[np.ndarray] = None
    adapter_id: int = 0
    arrival_ts: Optional[float] = None
    hashes: List[bytes] = field(default_factory=list)
    replica: Optional[str] = None        # current placement (None = queued)
    local_id: Optional[int] = None       # runner-side request id
    generated: List[int] = field(default_factory=list)
    done: bool = False
    migrations: int = 0
    affinity_blocks: int = 0             # resident blocks at placement time
    # request-scoped trace context (serving/tracing.py): minted at submit,
    # threaded through every placement so the replicas' lifecycle events and
    # the router journal join into one causal span tree per request
    trace_id: Optional[str] = None
    # SLA class (serving/sla.py): the tenant tier — priority placement,
    # weighted-fair budgets, brown-out shed order, preemption victimhood
    sla_class: Optional[str] = None
    # router-level SLA preemptions this request suffered (it re-queued and
    # resumed bit-exactly each time; distinct from replica-local preemptions)
    class_preemptions: int = 0
    # disaggregated pools (serving/pools.py): a completed KV handoff pins the
    # re-queued request to the destination replica holding its blocks — the
    # next _choose honors the pin (if that replica still admits) then clears it
    pin_replica: Optional[str] = None


class PrefixAffinityRouter:
    """Place requests over N EngineReplicas by prefix affinity + load.

    ``policy``: ``"affinity"`` (default), ``"load"`` (headroom/queue only),
    ``"random"`` (uniform over admitting replicas — the bench's control
    arm for the affinity-hit comparison), or ``"remote_prefill"``
    (disaggregated pools, serving/pools.py: arrivals place on prefill-pool
    replicas, decoding requests on decode-pool replicas, with a
    :class:`~.pools.PoolManager` live-handing their KV blocks across —
    affinity scoring applies WITHIN the chosen pool).
    """

    def __init__(self, replicas: Sequence[EngineReplica],
                 policy: str = "affinity", seed: int = 0, *,
                 fault_injector=None, max_retries: int = 3,
                 max_backoff_steps: int = 32,
                 watchdog_stall_s: Optional[float] = None,
                 debug_bundle_dir: Optional[str] = None,
                 auto_recover: bool = False,
                 shed_queue_depth: Optional[int] = None,
                 slo_signal=None, sla_classes=None,
                 preemptive: Optional[bool] = None,
                 brownout_up_after: int = 3, brownout_down_after: int = 5,
                 brownout_decode_cap: int = 1,
                 shed_retry_after_s: float = 1.0,
                 pool_config: Optional[dict] = None,
                 journal_prompts: bool = False):
        """Supervision knobs (fault tolerance, ISSUE-11):

        ``fault_injector``: a :class:`~.faults.FaultInjector` to attach
        (wraps the replica seams; test/bench harness).
        ``max_retries``: consecutive failures before a replica goes FAILED
        (each retry backs off ``2**streak`` router steps, capped at
        ``max_backoff_steps``).
        ``watchdog_stall_s``: wall-clock ceiling for one ``rep.step()`` —
        exceeding it counts as a ``stall`` failure (None = watchdog off).
        ``debug_bundle_dir``: where the automatic on-FAILED flight-recorder
        debug bundle lands (None = skip the dump, still log).
        ``auto_recover``: run :meth:`recover_replica` immediately on the
        transition to FAILED.
        ``shed_queue_depth``: arrival-queue depth past which ``submit``
        sheds (raises :class:`RouterOverloaded`) — only while ``slo_signal``
        (a callable returning True when healthy) says unhealthy, or always
        past the bound when no signal is given. None = never shed.

        Overload control plane (ISSUE-13):

        ``sla_classes``: an :class:`~.sla.SLAClassSet`. Turns on priority
        placement (most-important class places first), per-class admission,
        the brown-out ladder, and class preemption; every replica runner
        must have been built with the SAME set (weighted-fair budgets read
        it inside ``_step_mixed``).
        ``preemptive``: may a high-class arrival that cannot place preempt
        the NEWEST lowest-class running request? (victim re-queues and
        resumes bit-exactly — migrate or park-in-tier). Default: True when
        ``sla_classes`` is given.
        ``brownout_up_after`` / ``brownout_down_after``: consecutive
        unhealthy/healthy ``slo_signal`` readings (one per ``step()``)
        before the brown-out level rises/falls — the hysteresis.
        ``brownout_decode_cap``: max CONCURRENT placements of a class whose
        "cap" ladder rung is active (fleet-wide).
        ``shed_retry_after_s``: Retry-After unit — a level-L shed carries
        ``retry_after_s = L * shed_retry_after_s``.
        """
        if not replicas:
            raise ValueError("need at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"replica ids must be unique, got {ids}")
        if policy not in ("affinity", "load", "random", "remote_prefill"):
            raise ValueError(f"unknown placement policy {policy!r}")
        self.replicas: Dict[str, EngineReplica] = {
            r.replica_id: r for r in replicas}
        self.policy = policy
        self._rng = np.random.default_rng(seed)
        paged = {r.runner.paged for r in replicas}
        if len(paged) != 1:
            raise ValueError("replicas must agree on paged vs dense serving")
        self.paged = paged.pop()
        if self.paged:
            sizes = {r.runner.block_size for r in replicas}
            if len(sizes) != 1:
                raise ValueError("replicas must share one pa_block_size "
                                 f"(got {sorted(sizes)})")
            self.block_size = sizes.pop()
        else:
            self.block_size = 0
        self.queue: List[RouterRequest] = []
        self.requests: Dict[int, RouterRequest] = {}
        self._next_id = 0
        # (replica_id, local_id) -> global request id
        self._local: Dict[tuple, int] = {}
        # affinity needs more than a prefix cache being ON: the router must
        # be able to SEE each replica's resident hashes. The native C++
        # allocator keeps its hash table internal, so a fleet on it honestly
        # degrades to load placement (and bench's honesty guard refuses to
        # publish affinity numbers) instead of scoring every replica 0.
        self.prefix_caching = self.paged and all(
            getattr(r.runner.allocator, "enable_prefix_caching", False)
            and hasattr(r.runner.allocator, "hash_to_block")
            for r in replicas)

        reg = metrics_lib.MetricsRegistry()
        self.registry = reg
        self._c_submitted = reg.counter(
            "router_requests_total", "requests accepted by the frontend")
        self._c_placed = reg.counter(
            "router_placements_total", "request placements onto replicas "
            "(migrations re-count)")
        self._c_finished = reg.counter(
            "router_requests_finished_total", "requests fully served")
        self._c_tokens = reg.counter(
            "router_tokens_total", "tokens emitted across all replicas")
        self._c_aff_hits = reg.counter(
            "router_prefix_affinity_hits_total",
            "placements that landed on a replica already holding >=1 "
            "leading prompt block")
        self._c_aff_blocks = reg.counter(
            "router_prefix_affinity_blocks_total",
            "resident leading blocks at placement (skipped prefill, blocks)")
        self._c_cluster_aff_hits = reg.counter(
            "router_cluster_affinity_hits_total",
            "placements whose affinity score counted >=1 CLUSTER-resident "
            "block (fleet-warm prompt served without local warmth)")
        self._c_cluster_aff_blocks = reg.counter(
            "router_cluster_affinity_blocks_total",
            "cluster-resident leading blocks at placement (pulled instead "
            "of re-prefilled)")
        self._c_spills = reg.counter(
            "router_affinity_spills_total",
            "placements diverted off a saturated affinity target")
        self._c_spill_blocks = reg.counter(
            "router_affinity_lost_blocks_total",
            "resident blocks LOST to spills (recompute bought latency)")
        self._c_migrations = reg.counter(
            "router_migrations_total",
            "requests re-placed by a replica drain")
        self._g_queue = reg.gauge(
            "router_queue_depth", "requests waiting at the frontend")
        # --- replica supervision / fault tolerance (ISSUE-11) --------------
        self.max_retries = int(max_retries)
        self.max_backoff_steps = int(max_backoff_steps)
        self.watchdog_stall_s = watchdog_stall_s
        self.debug_bundle_dir = debug_bundle_dir
        self.auto_recover = auto_recover
        self.shed_queue_depth = shed_queue_depth
        self.slo_signal = slo_signal
        # --- SLA classes + brown-out ladder (ISSUE-13) ----------------------
        if sla_classes is not None:
            from .sla import SLAClassSet

            if not isinstance(sla_classes, SLAClassSet):
                raise ValueError("sla_classes must be a serving.sla."
                                 "SLAClassSet (or None)")
        self.sla = sla_classes
        if sla_classes is not None:
            # every replica runner must share the class set: a mismatch
            # would otherwise surface as a ValueError from runner.submit
            # MID-place_queued, leaving already-placed requests still queued
            # (double-placement on the next wave)
            for rep in replicas:
                self._check_replica_classes(rep)
        self.preemptive = (bool(preemptive) if preemptive is not None
                           else sla_classes is not None)
        if self.preemptive and sla_classes is None:
            raise ValueError("preemptive=True requires sla_classes")
        self.brownout_up_after = int(brownout_up_after)
        self.brownout_down_after = int(brownout_down_after)
        self.brownout_decode_cap = int(brownout_decode_cap)
        self.shed_retry_after_s = float(shed_retry_after_s)
        # the LADDER: rung L applies the first L actions. Built from the
        # class set's shed order (least-important sheddable classes first,
        # top class excluded): shed class arrivals FIRST, then cap its
        # decode concurrency, then move one class up — degradation never
        # touches top-class traffic (ISSUE-13 tentpole d)
        self._ladder: List[tuple] = []
        if sla_classes is not None:
            for cls in sla_classes.shed_order():
                self._ladder.append(("shed", cls))
                self._ladder.append(("cap", cls))
        self._brownout_level = 0
        self._unhealthy_streak = 0
        self._healthy_streak = 0
        # tokens folded OUTSIDE a step's replica sweep (the SLA preemption's
        # pipeline flush) — merged into the next step()'s returned emissions
        self._pending_emitted: Dict[int, List[int]] = {}
        self._g_brownout = reg.gauge(
            "router_brownout_level",
            "current brown-out ladder rung (0 = no degradation)")
        self._g_brownout.set(0)
        self._c_brownout: Dict[str, object] = {}       # direction -> counter
        self._c_class_shed: Dict[str, object] = {}     # class -> counter
        self._c_class_preempt: Dict[str, object] = {}  # victim class -> counter
        self._c_class_deferred: Dict[str, object] = {} # class -> counter
        self._step_count = 0
        self._health: Dict[str, str] = {}
        self._fail_streak: Dict[str, int] = {rid: 0 for rid in self.replicas}
        self._retry_after: Dict[str, int] = {rid: 0 for rid in self.replicas}
        self.recovery_times_ms: List[float] = []
        self._c_failures: Dict[tuple, object] = {}       # (replica, reason)
        self._g_state = {
            rid: reg.gauge(
                "serving_replica_state",
                "replica lifecycle: 0 healthy, 1 degraded, 2 failed",
                labels={"replica": rid})
            for rid in self.replicas}
        for rid in self.replicas:
            self._set_state(rid, REPLICA_HEALTHY)
        self._c_recoveries = reg.counter(
            "router_recoveries_total",
            "non-cooperative replica recoveries (recover_replica)")
        self._c_recovered = reg.counter(
            "router_recovered_requests_total",
            "in-flight requests rebuilt from the router journal and "
            "re-queued by recover_replica")
        self._c_shed = reg.counter(
            "router_requests_shed_total",
            "arrivals refused by the overload shed (queue past "
            "shed_queue_depth while the SLO signal is unhealthy)")
        self._c_aff_unavail = reg.counter(
            "router_affinity_unavailable_total",
            "placements whose best prefix holder was draining/degraded/"
            "failed — re-scored against the healthy set, lost hit counted")
        # --- request tracing (serving/tracing.py) ---------------------------
        # the router journal doubles as the trace spine: trace ids are minted
        # here and every placement / migration / recovery decision is an
        # event, so a request's history survives any single replica's death
        import uuid

        self.trace_epoch = time.perf_counter()
        self._trace_salt = uuid.uuid4().hex[:8]
        self.trace_events: List[dict] = []
        # in-memory retention bound, mirroring ServingTelemetry.max_records:
        # past it the OLDEST quarter drops (counted — a long-lived frontend
        # must not grow one journal dict per event forever; spool with
        # write_trace_events for the full history)
        self.max_trace_events = 200_000
        self._c_trace_dropped = reg.counter(
            "router_trace_events_dropped_total",
            "journal events evicted past the in-memory retention bound")
        # --- disaggregated pools (serving/pools.py) -------------------------
        # under remote_prefill the PoolManager owns the prefill→decode KV
        # handoffs; its tick runs inside step() after the replica sweep.
        # pool_config forwards PoolManager kwargs (e.g. channel="tier").
        if policy == "remote_prefill":
            from .pools import PoolManager

            self.pools = PoolManager(self, **(pool_config or {}))
        else:
            if pool_config is not None:
                raise ValueError("pool_config requires policy="
                                 "'remote_prefill'")
            self.pools = None
        # ``journal_prompts``: journal each submit's PROMPT TOKENS alongside
        # the metadata it already records. This is what makes the journal a
        # replayable arrival trace (serving/replay.py reconstructs prompts,
        # timestamps, classes, and trace ids from it) — off by default
        # because prompts are payload, not telemetry: a production journal
        # should not retain user content unless the operator opted in.
        self.journal_prompts = bool(journal_prompts)
        # --- live knob table (serving/knobs.py, ISSUE-18) --------------------
        # router-scope overload thresholds, enumerated + gauge-exported so
        # the tuner can drive them and the audit trail can show them
        from .knobs import build_router_knobs

        self.knobs = build_router_knobs(self)
        self.fault_injector = fault_injector
        if fault_injector is not None:
            fault_injector.attach(self)

    # ------------------------------------------------------------- tracing
    def _trace_event(self, event: str, req: Optional[RouterRequest] = None,
                     **fields) -> None:
        rec = {"ts": time.perf_counter() - self.trace_epoch, "event": event}
        if req is not None:
            rec["trace_id"] = req.trace_id
            rec["request_id"] = req.request_id
        rec.update(fields)
        self.trace_events.append(rec)
        if (self.max_trace_events is not None
                and len(self.trace_events) > self.max_trace_events):
            n = self.max_trace_events // 4
            del self.trace_events[:n]
            self._c_trace_dropped.inc(n)

    def trace_source(self) -> Dict[str, object]:
        """This journal as a tracing source (serving/tracing.py)."""
        from . import tracing

        return tracing.source_from_router(self)

    def write_trace_events(self, path: str) -> str:
        """Spool the router journal as JSONL (same epoch-header convention
        the telemetry spools use, so scripts/explain_request.py merges the
        files offline on the shared clock)."""
        with open(path, "w") as fh:
            fh.write(json.dumps({"event": "telemetry_epoch",
                                 "epoch": self.trace_epoch,
                                 "unix_ts": time.time()}) + "\n")
            for rec in self.trace_events:
                fh.write(json.dumps(rec) + "\n")
        return path

    # ------------------------------------------------------------- lifecycle state
    def _set_state(self, rid: str, state: str) -> None:
        self._health[rid] = state
        self._g_state[rid].set(_STATE_GAUGE[state])

    def replica_state(self, replica_id: str) -> str:
        return self._health[replica_id]

    def _placeable(self, rep: EngineReplica) -> bool:
        """In the placement set: HEALTHY and not draining. DEGRADED replicas
        are backing off a failure (their next step may fail again) and
        FAILED replicas are gone — neither takes new work."""
        return (self._health[rep.replica_id] == REPLICA_HEALTHY
                and not rep.draining)

    # ---------------------------------------------------------------- intake
    def _shed(self, sla_class: Optional[str], reason: str, msg: str) -> None:
        """One typed shed: counted (total + per class), journaled, logged,
        raised with the class and a Retry-After hint."""
        self._c_shed.inc()
        if sla_class is not None:
            c = self._c_class_shed.get(sla_class)
            if c is None:
                c = self.registry.counter(
                    "router_class_shed_total",
                    "arrivals shed by class (brown-out ladder + queue bound)",
                    labels={"sla_class": sla_class})
                self._c_class_shed[sla_class] = c
            c.inc()
        retry = self.shed_retry_after_s * max(1, self._brownout_level)
        self._trace_event("shed", queue_depth=len(self.queue),
                          sla_class=sla_class, reason=reason,
                          brownout_level=self._brownout_level)
        logger.warning("shedding arrival (%s, class=%s): %s", reason,
                       sla_class, msg)
        raise RouterOverloaded(msg, sla_class=sla_class, retry_after_s=retry)

    def _brownout_actions(self) -> Dict[str, set]:
        """Classes currently shed / capped by the active ladder rungs."""
        out = {"shed": set(), "cap": set()}
        for kind, cls in self._ladder[: self._brownout_level]:
            out[kind].add(cls)
        return out

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, sampling_params=None,
               adapter_id: int = 0, arrival_ts: Optional[float] = None,
               sla_class: Optional[str] = None) -> int:
        prompt = np.asarray(prompt).astype(np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if self.sla is not None:
            sla_class = self.sla.resolve(sla_class)    # unknown class raises
        elif sla_class is not None:
            raise ValueError("sla_class given but the router has no "
                             "sla_classes set")
        # brown-out admission (ISSUE-13 tentpole d): at the current ladder
        # rung the class's arrivals are shed outright — lowest classes go
        # first, the top class is never on the ladder
        if sla_class is not None and self._brownout_level > 0 \
                and sla_class in self._brownout_actions()["shed"]:
            self._shed(sla_class, "brownout",
                       f"class {sla_class!r} shed at brown-out level "
                       f"{self._brownout_level}")
        if (self.shed_queue_depth is not None
                and len(self.queue) >= self.shed_queue_depth
                and (self.slo_signal is None or not self.slo_signal())):
            # graceful degradation under exhaustion/overload: shed by SLO
            # signal at the frontend instead of queueing into a wedge —
            # counted, logged, surfaced to the caller as a typed error
            self._shed(sla_class, "queue_bound",
                       f"frontend queue depth {len(self.queue)} >= shed "
                       f"bound {self.shed_queue_depth}")
        req = RouterRequest(
            self._next_id, prompt, max_new_tokens, eos_token_id,
            None if sampling_params is None
            else np.asarray(sampling_params, dtype=np.float32).reshape(-1),
            adapter_id, arrival_ts,
            hashes=(prompt_block_hashes(prompt, self.block_size, adapter_id)
                    if self.paged else []),
            sla_class=sla_class)
        req.trace_id = f"t-{self._trace_salt}-{req.request_id:06x}"
        self._next_id += 1
        self.requests[req.request_id] = req
        self.queue.append(req)
        self._c_submitted.inc()
        self._g_queue.set(len(self.queue))
        self._trace_event("submit", req, prompt_len=int(prompt.size),
                          max_new_tokens=max_new_tokens, sla_class=sla_class,
                          **({"prompt": prompt.tolist(),
                              "eos_token_id": eos_token_id,
                              "adapter_id": adapter_id}
                             if self.journal_prompts else {}))
        return req.request_id

    # ------------------------------------------------------------- placement
    def _affinity(self, req: RouterRequest) -> Dict[str, int]:
        return {rid: rep.resident_prefix_blocks(req.hashes)
                for rid, rep in self.replicas.items()
                if self._placeable(rep)}

    def _load_key(self, rep: EngineReplica):
        """Sort key: most KV headroom first, then shallowest queue, then
        fewest live rows — ties broken by id for determinism."""
        a = rep.admission()
        return (-a.get("kv_blocks_free", 0), a["queue_depth"],
                a["active_requests"], rep.replica_id)

    def _pool_filter(self, req: RouterRequest,
                     admitting: List[EngineReplica]) -> List[EngineReplica]:
        """remote_prefill placement (serving/pools.py): fresh arrivals go to
        the prefill pool, decoding (resumed / handed-off) requests to the
        decode pool; unified replicas serve both. A ``pin_replica`` from a
        completed handoff wins outright — the destination already holds the
        request's KV blocks. When the wanted pool has a placeable member
        that merely cannot admit YET, return [] so the request WAITS for its
        pool (cross-phase interference is exactly what disaggregation
        removes); only when the wanted pool is gone entirely does placement
        fall back to whatever admits — availability over topology."""
        if req.pin_replica is not None:
            pinned = [r for r in admitting
                      if r.replica_id == req.pin_replica]
            if pinned:
                return pinned
            # pin target failed/full: clear it and fall through to normal
            # pool scoring (the handed-off blocks are a lost affinity hit)
            req.pin_replica = None
        want = (("decode", "unified") if req.generated
                else ("prefill", "unified"))
        subset = [r for r in admitting if r.pool_role in want]
        if subset:
            return subset
        if any(self._placeable(r) and r.pool_role in want
               for r in self.replicas.values()):
            return []
        return admitting

    def _choose(self, req: RouterRequest):
        """Returns (replica, affinity_blocks, spilled_from) or None when no
        replica can admit the request right now."""
        # a migrated request refeeds prompt + generated at placement, so its
        # KV footprint is the FULL stream so far, not the prompt alone
        n = len(req.prompt) + len(req.generated)
        # only HEALTHY, non-draining replicas take placements: can_admit
        # alone knows nothing about the supervision lifecycle, and placing
        # onto a DEGRADED/FAILED replica would strand the request behind a
        # failure the router already knows about
        admitting = [r for r in self.replicas.values()
                     if self._placeable(r) and r.can_admit(n)]
        if self.pools is not None:
            admitting = self._pool_filter(req, admitting)
        if not admitting:
            return None
        if self.policy == "random":
            rep = admitting[int(self._rng.integers(len(admitting)))]
            return rep, rep.resident_prefix_blocks(req.hashes), None
        if self.policy == "load" or not self.prefix_caching:
            rep = min(admitting, key=self._load_key)
            return rep, rep.resident_prefix_blocks(req.hashes), None
        aff = self._affinity(req)
        best_aff = max((aff.get(r.replica_id, 0) for r in admitting),
                       default=0)
        # a draining/degraded/failed replica may hold a LONGER prefix than
        # any placeable one: the request re-scores against the healthy set
        # (it must NOT place on a non-healthy holder) and the lost hit is
        # counted — recompute bought availability, visibly
        best_unavail = 0
        for rid, rep in self.replicas.items():
            if not self._placeable(rep):
                try:
                    best_unavail = max(best_unavail,
                                       rep.resident_prefix_blocks(req.hashes))
                # a dead replica's probe may raise — its blocks are
                # unreachable anyway, which is exactly "no affinity"
                # lint: ok(silent-except): dead-replica affinity probe; the blocks it would score are unreachable
                except Exception:
                    pass
        if best_unavail > best_aff:
            self._c_aff_unavail.inc()
        if best_aff > 0:
            targets = [r for r in admitting
                       if aff.get(r.replica_id, 0) == best_aff]
            # affinity target with immediate headroom wins; a saturated
            # target spills to the best-by-load admitting replica
            ready = [r for r in targets if r.has_headroom(n)]
            if ready:
                rep = min(ready, key=self._load_key)
                return rep, best_aff, None
            others = [r for r in admitting if r not in targets]
            ready_others = [r for r in others if r.has_headroom(n)]
            if ready_others:
                rep = min(ready_others, key=self._load_key)
                return rep, aff.get(rep.replica_id, 0), best_aff
            # nobody has immediate headroom: queue on the affinity target
            # (the hit survives the wait)
            rep = min(targets, key=self._load_key)
            return rep, best_aff, None
        rep = min(admitting, key=self._load_key)
        return rep, 0, None

    def _live_class_count(self, cls: str) -> int:
        """CONCURRENT placements of a class, fleet-wide (brown-out cap).
        Walks ``_local`` — live placements only, since finished entries are
        pruned at _fold — not the ever-growing ``requests`` journal."""
        return sum(1 for gid in set(self._local.values())
                   if self.requests[gid].sla_class == cls
                   and self.requests[gid].replica is not None
                   and not self.requests[gid].done)

    def _defer_capped(self, req: RouterRequest) -> None:
        c = self._c_class_deferred.get(req.sla_class)
        if c is None:
            c = self.registry.counter(
                "router_class_placements_deferred_total",
                "placements deferred by the brown-out decode-concurrency cap",
                labels={"sla_class": req.sla_class})
            self._c_class_deferred[req.sla_class] = c
        c.inc()

    def place_queued(self) -> int:
        """Place as many queued requests as replicas will admit. Classless:
        FIFO (unchanged). With SLA classes: most-important class first (FIFO
        within a class — request ids are arrival order), brown-out decode
        caps honored, and a high-class request that cannot place may preempt
        the newest lowest-class victim (``preemptive``). Returns the number
        placed this call."""
        placed = 0
        if self.sla is not None:
            ordered = sorted(self.queue,
                             key=lambda r: (self.sla.priority(r.sla_class),
                                            r.request_id))
            capped = self._brownout_actions()["cap"]
        else:
            ordered = list(self.queue)
            capped = set()
        remaining: List[RouterRequest] = []
        displaced: List[RouterRequest] = []      # preemption victims, re-queued
        for req in ordered:
            if (req.sla_class in capped
                    and self._live_class_count(req.sla_class)
                    >= self.brownout_decode_cap):
                # brown-out rung "cap": the class keeps at most
                # brownout_decode_cap concurrent streams — deferred, not
                # lost (it places when a stream of its class finishes)
                self._defer_capped(req)
                remaining.append(req)
                continue
            choice = self._choose(req)
            if self.preemptive and req.sla_class is not None:
                # "can't place" for a classed request means no healthy
                # replica can take it IMMEDIATELY (admitting into a queue
                # behind lower-class streams is exactly the starvation the
                # preemptive tier exists to break): preempt the newest
                # lowest-class victim, then re-choose — the freed slot (and
                # its blocks) admit the high-class request this wave
                n = len(req.prompt) + len(req.generated)
                immediate = any(
                    self._placeable(r) and r.has_headroom(n)
                    for r in self.replicas.values())
                # feasibility: evicting victims can only help if SOME healthy
                # replica's pool could ever hold the request — a request no
                # pool can fit must not churn lower-class streams every wave
                feasible = any(
                    self._placeable(r)
                    and (not r.runner.paged
                         or r.blocks_needed(n) <= r.runner.allocator.num_blocks)
                    for r in self.replicas.values())
                if (choice is None or not immediate) and feasible:
                    victim = self._preempt_for(req)
                    if victim is not None:
                        displaced.append(victim)
                        choice = self._choose(req)
            if choice is None:
                remaining.append(req)
                continue
            rep, aff_blocks, lost = choice
            self._place(req, rep, aff_blocks, lost)
            placed += 1
        self.queue = remaining + displaced
        self._g_queue.set(len(self.queue))
        return placed

    def _preempt_for(self, req: RouterRequest) -> Optional[RouterRequest]:
        """Preemptive priorities (ISSUE-13 tentpole c): evict the NEWEST
        victim of the LOWEST class strictly below ``req``'s, through the
        runner's existing mid-prompt preempt path (``evict_request``). The
        victim's committed prefix parks in the idle pool / host KV tier
        (tiered allocators) and the request re-queues — it migrates to
        whichever replica next admits it and resumes bit-exactly via
        ``submit(resume_tokens=)``. Returns the displaced RouterRequest (to
        re-queue), or None when no strictly-lower-class victim exists."""
        my_p = self.sla.priority(req.sla_class)
        victim = None
        vkey = None
        for (rid, _local), gid in self._local.items():
            v = self.requests[gid]
            if v.done or v.replica != rid:
                continue
            if self._health.get(rid) != REPLICA_HEALTHY:
                continue           # a dead replica cannot cooperate
            vp = self.sla.priority(v.sla_class)
            if vp <= my_p:
                continue           # only strictly lower classes are victims
            key = (vp, gid)        # lowest class first, then newest placed
            if vkey is None or key > vkey:
                vkey, victim = key, v
        if victim is None:
            return None
        rep = self.replicas[victim.replica]
        rid, local_id = victim.replica, victim.local_id
        emitted, _evicted = rep.evict_request(local_id)
        # the eviction's pipeline flush may still commit tokens (they belong
        # to their streams) — fold them into the PENDING buffer, which the
        # enclosing step() merges into its returned emissions (a stream that
        # finishes inside the flush must still reach a streaming consumer)
        for lid, toks in emitted.items():
            self._fold(rid, lid, toks, self._pending_emitted)
        self._local.pop((rid, local_id), None)
        victim.replica = None
        victim.local_id = None
        if victim.done:
            # the flush finished it — nothing to re-queue, but headroom
            # opened all the same
            return None
        victim.migrations += 1
        victim.class_preemptions += 1
        c = self._c_class_preempt.get(victim.sla_class)
        if c is None:
            c = self.registry.counter(
                "router_class_preemptions_total",
                "requests preempted by a higher-SLA-class arrival",
                labels={"victim_class": victim.sla_class})
            self._c_class_preempt[victim.sla_class] = c
        c.inc()
        self._trace_event("class_preempt", victim, from_replica=rid,
                          for_request=req.request_id,
                          for_class=req.sla_class,
                          tokens_so_far=len(victim.generated))
        logger.info(
            "SLA preemption: request %d (%s) evicted from replica %s for "
            "request %d (%s); it re-queues and resumes bit-exactly",
            victim.request_id, victim.sla_class, rid, req.request_id,
            req.sla_class)
        return victim

    def _place(self, req: RouterRequest, rep: EngineReplica,
               aff_blocks: int, lost: Optional[int]) -> None:
        kw = dict(max_new_tokens=req.max_new_tokens,
                  eos_token_id=req.eos_token_id,
                  adapter_id=req.adapter_id, arrival_ts=req.arrival_ts,
                  trace_id=req.trace_id)
        if req.sampling_params is not None:
            kw["sampling_params"] = req.sampling_params
        if req.sla_class is not None:
            # the runner re-validates against ITS class set (the fleet must
            # share one; a mismatch raises at placement, never silently)
            kw["sla_class"] = req.sla_class
        if req.generated:
            kw["resume_tokens"] = req.generated
        req.local_id = rep.submit(req.prompt, **kw)
        req.replica = rep.replica_id
        req.affinity_blocks = aff_blocks
        req.pin_replica = None          # a handoff pin is one-shot
        self._local[(rep.replica_id, req.local_id)] = req.request_id
        self._c_placed.inc()
        self._trace_event("place", req, replica=rep.replica_id,
                          local_id=req.local_id, affinity_blocks=aff_blocks,
                          spilled_from=lost, migrations=req.migrations,
                          policy=self.policy)
        if aff_blocks > 0:
            self._c_aff_hits.inc()
            self._c_aff_blocks.inc(aff_blocks)
            residency = getattr(rep, "prefix_residency", None)
            if residency is not None and req.hashes:
                cl = residency(req.hashes)[2]
                if cl > 0:
                    self._c_cluster_aff_hits.inc()
                    self._c_cluster_aff_blocks.inc(cl)
        if lost is not None:
            self._c_spills.inc()
            self._c_spill_blocks.inc(max(0, lost - aff_blocks))

    # ------------------------------------------------------------- serving
    def step(self) -> Dict[int, List[int]]:
        """One serving wave: place what fits, step every replica with work,
        fold each replica's emissions back to frontend request ids.

        SUPERVISED (ISSUE-11): a per-replica failure no longer kills the
        frontend. Exceptions from ``rep.step()`` are caught and counted; the
        replica degrades, backs off, retries, and FAILS after
        ``max_retries`` consecutive failures (or immediately on hard
        death); a wall-clock stall past ``watchdog_stall_s`` counts as a
        failure too. FAILED replicas are skipped entirely (their streams
        move via recover_replica)."""
        self._step_count += 1
        self._update_brownout()
        self.place_queued()
        # emissions folded during placement (SLA-preemption pipeline flush)
        # belong to this step's output
        emitted: Dict[int, List[int]] = self._pending_emitted
        self._pending_emitted = {}
        for rid, rep in list(self.replicas.items()):
            if self._health[rid] == REPLICA_FAILED:
                continue
            if self._step_count < self._retry_after[rid]:
                continue                      # backing off a recent failure
            if not rep.has_work:
                if self._health[rid] == REPLICA_DEGRADED:
                    # nothing to retry against; an idle degraded replica
                    # rejoins the placement set
                    self._note_step_ok(rid)
                continue
            t0 = time.perf_counter()
            try:
                step_out = rep.step()
            # lint: ok(silent-except): THE supervisor handler — _on_replica_failure counts router_replica_failures_total and logs every failure
            except Exception as e:
                self._on_replica_failure(rid, e)
                continue
            wall = time.perf_counter() - t0
            if (self.watchdog_stall_s is not None
                    and wall > self.watchdog_stall_s):
                # the router-level dispatch gap (PR 7's stall signal at
                # this altitude): the step RETURNED but took far too long —
                # a wedged dispatch inside it. Counted like a failure;
                # repeated stalls fail the replica.
                self._on_replica_failure(rid, None, reason="stall",
                                         wall_s=wall)
            else:
                self._note_step_ok(rid)
            for local_id, toks in step_out.items():
                self._fold(rid, local_id, toks, emitted)
        if self.pools is not None:
            # drive prefill→decode handoffs on the freshest insert progress
            # (right after the sweep); emissions a finalize's eviction flush
            # produces land in _pending_emitted and merge into the NEXT
            # step's output — the SLA-preemption convention
            self.pools.tick()
        return emitted

    def _check_replica_classes(self, rep: EngineReplica) -> None:
        """A classed router requires every replica runner to carry the SAME
        class set — full value equality (priorities, weights, shed flags,
        default), not just names: a runner weighting `bulk` 4x while the
        router preempts bulk victims would be contradictory policy with no
        error. Checked at construction/add time, not mid-placement."""
        rsla = getattr(rep.runner, "sla", None)
        if (rsla is None or list(rsla) != list(self.sla)
                or rsla.default != self.sla.default):
            raise ValueError(
                f"replica {rep.replica_id!r} runner was not built with the "
                f"router's sla_classes (runner: {rsla!r}, router: "
                f"{self.sla!r}); pass the same SLAClassSet to every "
                f"ContinuousBatchingRunner")

    # ----------------------------------------------------------- brown-out
    def _update_brownout(self) -> None:
        """One ``slo_signal`` reading per router step, hysteresis-gated:
        ``brownout_up_after`` consecutive unhealthy readings raise the
        ladder one rung, ``brownout_down_after`` consecutive healthy ones
        lower it. No SLA classes / no signal / empty ladder = inert."""
        if not self._ladder or self.slo_signal is None:
            return
        if bool(self.slo_signal()):
            self._healthy_streak += 1
            self._unhealthy_streak = 0
            if (self._brownout_level > 0
                    and self._healthy_streak >= self.brownout_down_after):
                self._set_brownout(self._brownout_level - 1, "down")
                self._healthy_streak = 0
        else:
            self._unhealthy_streak += 1
            self._healthy_streak = 0
            if (self._brownout_level < len(self._ladder)
                    and self._unhealthy_streak >= self.brownout_up_after):
                self._set_brownout(self._brownout_level + 1, "up")
                self._unhealthy_streak = 0

    def _set_brownout(self, level: int, direction: str) -> None:
        """One ladder transition: gauge + per-direction counter + journal
        event, and the degradation is STAMPED on every healthy replica's
        next step-timeline record through the runner's ``_fall_through``
        reason plumbing — a browned-out fleet is visible in the same place
        a degraded scheduler is, never silent."""
        self._brownout_level = level
        self._g_brownout.set(level)
        c = self._c_brownout.get(direction)
        if c is None:
            c = self.registry.counter(
                "router_brownout_transitions_total",
                "brown-out ladder transitions", labels={"direction": direction})
            self._c_brownout[direction] = c
        c.inc()
        acts = self._brownout_actions()
        self._trace_event("brownout", level=level, direction=direction,
                          shed=sorted(acts["shed"]), cap=sorted(acts["cap"]))
        logger.warning(
            "brown-out level %d (%s): shedding %s, capping %s (decode cap "
            "%d)", level, direction, sorted(acts["shed"]) or "nothing",
            sorted(acts["cap"]) or "nothing", self.brownout_decode_cap)
        self.stamp_fleet("brownout", f"{direction}_level_{level}")

    def stamp_fleet(self, from_kind: str, reason: str,
                    detail: Optional[str] = None) -> None:
        """Stamp one control-plane decision onto every healthy replica's
        next step-timeline record (the runner ``_fall_through`` plumbing) —
        THE shared mechanism for brown-out transitions, autoscaler
        grow/drain/retire, and tuner knob decisions, so ``explain_request``
        can show why the fleet changed shape mid-request. ``detail`` rides
        the timeline note only (never the counter labels)."""
        for rid, rep in self.replicas.items():
            if self._health.get(rid) != REPLICA_HEALTHY:
                continue
            try:
                rep.runner._note_fall_through(from_kind, reason,
                                              detail=detail)
            # lint: ok(silent-except): best-effort telemetry stamp; the decision is already counted+logged+journaled at its origin
            except Exception:
                pass

    def _note_step_ok(self, rid: str) -> None:
        if self._fail_streak[rid]:
            logger.info("replica %s recovered after %d failure(s)",
                        rid, self._fail_streak[rid])
        self._fail_streak[rid] = 0
        self._retry_after[rid] = 0
        if self._health[rid] == REPLICA_DEGRADED:
            self._set_state(rid, REPLICA_HEALTHY)

    def _count_failure(self, rid: str, reason: str) -> None:
        key = (rid, reason)
        c = self._c_failures.get(key)
        if c is None:
            c = self.registry.counter(
                "router_replica_failures_total",
                "replica step failures seen by the supervisor",
                labels={"replica": rid, "reason": reason})
            self._c_failures[key] = c
        c.inc()

    def _on_replica_failure(self, rid: str, exc: Optional[BaseException],
                            reason: Optional[str] = None,
                            wall_s: Optional[float] = None) -> None:
        if reason is None:
            reason = ("death" if isinstance(exc, InjectedReplicaDeath)
                      else "exception")
        self._count_failure(rid, reason)
        self._fail_streak[rid] += 1
        streak = self._fail_streak[rid]
        if reason == "death" or streak > self.max_retries:
            self._fail_replica(rid, reason, exc)
            return
        backoff = min(2 ** streak, self.max_backoff_steps)
        self._retry_after[rid] = self._step_count + backoff
        self._set_state(rid, REPLICA_DEGRADED)
        logger.warning(
            "replica %s %s (%s) — failure %d/%d, retrying in %d router "
            "step(s)", rid, reason,
            exc if exc is not None else f"step wall {wall_s:.3f}s > "
            f"watchdog {self.watchdog_stall_s:.3f}s",
            streak, self.max_retries, backoff)

    def _fail_replica(self, rid: str, reason: str,
                      exc: Optional[BaseException] = None) -> None:
        """The DEGRADED→FAILED (or straight-to-FAILED) transition: leave the
        placement set for good, dump the flight-recorder debug bundle, and
        (auto_recover) rebuild the replica's streams from the journal."""
        if self._health[rid] == REPLICA_FAILED:
            return
        self._set_state(rid, REPLICA_FAILED)
        self._trace_event("replica_failed", replica=rid, reason=reason)
        logger.error("replica %s FAILED (%s): %s — %s", rid, reason,
                     exc if exc is not None else "watchdog/stall",
                     "auto-recovering its streams" if self.auto_recover
                     else "awaiting recover_replica()")
        self._dump_failure_bundle(rid, reason, exc)
        if self.auto_recover:
            self.recover_replica(rid)

    def _dump_failure_bundle(self, rid: str, reason: str,
                             exc: Optional[BaseException]) -> str:
        """Automatic debug bundle on FAILED — best-effort by design: the
        bundle reads the (host-side) telemetry ring and registry, never the
        dead device, and a dump failure must not mask the failure being
        dumped."""
        if self.debug_bundle_dir is None:
            return ""
        rep = self.replicas[rid]
        flight = getattr(rep.runner.telemetry, "flight", None)
        if flight is None:
            logger.warning("replica %s has no flight recorder — no FAILED "
                           "debug bundle", rid)
            return ""
        path = os.path.join(self.debug_bundle_dir,
                            f"replica-{rid}-failed.json")
        try:
            # the span trees of everything in flight on the dead replica at
            # dump time: the post-mortem shows WHERE each stream was, not
            # just that streams existed (serving/tracing.py); the KV block
            # ledger snapshot names WHO holds the dead pool (memledger —
            # guarded the same way: a ledger failure never masks the fault)
            from . import memledger, tracing

            out = flight.dump_bundle(
                path, metrics=rep.registry.to_dict(), stats=None,
                reason=f"replica_failed:{reason}",
                spans=tracing.inflight_span_trees_safe(rep.runner.telemetry),
                extra={"replica": rid, "exception": repr(exc),
                       "router_step": self._step_count,
                       "fail_streak": self._fail_streak[rid],
                       "memory": memledger.snapshot_safe(rep.runner)})
            logger.warning("replica %s FAILED debug bundle: %s", rid, out)
            return out
        except Exception as e:
            logger.warning("replica %s FAILED debug-bundle dump failed: %s",
                           rid, e)
            return ""

    def _fold(self, rid: str, local_id: int, toks: List[int],
              emitted: Dict[int, List[int]]) -> None:
        gid = self._local.get((rid, local_id))
        if gid is None:                     # foreign submit, not ours
            return
        req = self.requests[gid]
        if toks:
            req.generated.extend(toks)
            emitted.setdefault(gid, []).extend(toks)
            self._c_tokens.inc(len(toks))
        rep = self.replicas[rid]
        local = rep.runner.finished.get(local_id)
        if local is not None and not req.done:
            req.done = True
            self._c_finished.inc()
            self._trace_event("finish", req, replica=rid,
                              tokens=len(req.generated))
            # prune the placement map: finished rows emit nothing further
            # (the runner's commit skips done rows), and keeping every entry
            # ever served would make the preemption/cap scans O(history)
            self._local.pop((rid, local_id), None)

    @property
    def has_work(self) -> bool:
        """Work the router can still make progress on: the arrival queue
        plus live replicas' work. A FAILED replica's roster does NOT count —
        its runner may hold ghost rows forever (that's why it failed); its
        real streams move to the queue via recover_replica."""
        return bool(self.queue) or any(
            rep.has_work for rid, rep in self.replicas.items()
            if self._health[rid] != REPLICA_FAILED)

    def _diagnostic_snapshot(self) -> Dict[str, object]:
        """What a wedged fleet looks like, from the exception alone: queue
        depth + head ids, and per replica its lifecycle state, backoff,
        work flag, and in-flight frontend request ids."""
        per_replica: Dict[str, object] = {}
        for rid, rep in self.replicas.items():
            inflight = sorted(gid for (r, _l), gid in self._local.items()
                              if r == rid
                              and not self.requests[gid].done)
            try:
                has_work = bool(rep.has_work)
            except Exception as e:   # lint: ok(silent-except): snapshot of a possibly-dead replica; the error IS the diagnostic
                has_work = f"unreadable: {e!r}"
            per_replica[rid] = {
                "state": self._health[rid],
                "draining": rep.draining,
                "has_work": has_work,
                "fail_streak": self._fail_streak[rid],
                "retry_after_step": self._retry_after[rid],
                "inflight_request_ids": inflight[:16],
            }
        return {
            "router_step": self._step_count,
            "queue_depth": len(self.queue),
            "queued_request_ids": [r.request_id for r in self.queue[:16]],
            "replicas": per_replica,
        }

    def run_to_completion(self, max_steps: int = 10000) -> Dict[int, List[int]]:
        guard = 0
        while self.has_work:
            self.step()
            guard += 1
            if guard > max_steps:
                # a wedged fleet must be debuggable from the exception
                # alone: who is queued, who holds what, who is backing off
                raise RuntimeError(
                    f"router serving did not converge after {max_steps} "
                    f"steps; diagnostic: "
                    f"{json.dumps(self._diagnostic_snapshot(), default=str)}")
        return {rid: req.generated for rid, req in self.requests.items()}

    # ------------------------------------------------------------- lifecycle
    def drain_replica(self, replica_id: str) -> int:
        """Remove a replica from the placement set: its live requests are
        preempted through the runner's mid-prompt preemption/resume path and
        re-queued at the FRONT of the arrival queue (they resume first, with
        their generated tokens carried via ``resume_tokens``). Returns the
        number of requests migrated. The replica object stays registered
        (``reactivate_replica`` re-adds it)."""
        rep = self.replicas[replica_id]
        emitted, evicted = rep.drain()
        # tokens committed by the pipeline flush still belong to the stream
        final: Dict[int, List[int]] = {}
        for local_id, toks in emitted.items():
            self._fold(replica_id, local_id, toks, final)
        migrated = 0
        for r in reversed(evicted):
            gid = self._local.pop((replica_id, r.request_id), None)
            if gid is None:
                continue
            req = self.requests[gid]
            req.replica = None
            req.local_id = None
            req.migrations += 1
            self.queue.insert(0, req)
            migrated += 1
            self._c_migrations.inc()
            self._trace_event("migrate_out", req, from_replica=replica_id,
                              tokens_so_far=len(req.generated))
        self._g_queue.set(len(self.queue))
        # migration audit point (serving/memledger.py): the drained pool
        # must balance before its streams re-place elsewhere — violations
        # log memledger_violation + count, never block the migration
        aud = getattr(rep.runner, "audit_ledger", None)
        if aud is not None:
            try:
                aud()
            except Exception as e:   # lint: ok(silent-except): the audit is observability; a broken ledger must not fail a healthy drain (logged below)
                logger.warning("post-drain ledger audit failed on replica "
                               "%s: %s", replica_id, e)
        logger.info("drained replica %s: %d requests re-queued for migration",
                    replica_id, migrated)
        return migrated

    def recover_replica(self, replica_id: str) -> int:
        """NON-cooperative crash recovery: rebuild every in-flight stream of
        a dead replica from the router's OWN journal — unlike
        ``drain_replica`` this never calls into the dead runner (no drain,
        no pipeline flush, no device work).

        - Every in-flight request maps back through ``_local`` to its
          :class:`RouterRequest`, which holds the full prompt and every
          COMMITTED token (``generated``); the request re-queues at the
          FRONT and re-places on a survivor via ``submit(resume_tokens=)``
          — greedy streams continue bit-identically (tokens the dead
          replica computed but never committed to the router were never
          emitted to a client, so recomputing them changes nothing
          observable).
        - The shared :class:`HostKVTier` is reconciled: host-byte
          reservations the dead replica held for queued re-admissions are
          restored to the store (host-side state, no cooperation needed);
          its device-resident blocks are written off (unreachable).
        - The replica is marked FAILED (placement/affinity/stepping all skip
          it) until ``reactivate_replica(replica_id, replica=<fresh>)``.

        Returns the number of requests re-queued."""
        t0 = time.perf_counter()
        rep = self.replicas[replica_id]
        if self._health[replica_id] != REPLICA_FAILED:
            self._set_state(replica_id, REPLICA_FAILED)
        # --- journal rebuild (no dead-runner involvement) -------------------
        moved: List[RouterRequest] = []
        for key in [k for k in self._local if k[0] == replica_id]:
            gid = self._local.pop(key)
            req = self.requests[gid]
            if req.done:
                continue
            req.replica = None
            req.local_id = None
            req.migrations += 1
            moved.append(req)
            # the journal is the ONLY witness of this window: the dead
            # replica's own event log ends mid-stream, so the span tree
            # synthesizes a `recovered` span from this event
            self._trace_event("recover", req, from_replica=replica_id,
                              resumed_tokens=len(req.generated))
        moved.sort(key=lambda r: r.request_id)       # arrival order
        for req in reversed(moved):
            self.queue.insert(0, req)                # resumes first
        self._g_queue.set(len(self.queue))
        # --- shared-tier reconciliation (host-side state only) --------------
        restored = 0
        try:
            tier = rep.runner.kv_tier
            if tier is not None:
                led = getattr(rep.runner, "ledger", None)
                for _blk, h, host_blk in \
                        rep.runner.allocator.take_pending_readmits():
                    tier.restore(h, host_blk)
                    if led is not None:
                        # the dead replica's device block stays with its
                        # ghost holder; the reservation is accounted for —
                        # not a stuck in-flight readmit
                        led.readmit_written_off(_blk)
                    restored += 1
        except Exception as e:
            # the dead replica's host state may itself be corrupt; its
            # reservations are then lost to the store (re-prefill covers
            # the prefixes) — visible, never fatal to the recovery
            logger.warning("tier reconciliation for dead replica %s "
                           "failed: %s", replica_id, e)
        # --- cluster-store reconciliation (fleet-side state) ----------------
        # Drop the dead owner's refcounts and abort its in-flight pulls so
        # the conservation auditor sees no ghost pins. Content-addressed
        # bytes stay: a published block outlives its publisher. Skip when
        # the TIER is shared with a live replica (its owner identity is the
        # tier's, which is still alive).
        try:
            tier = rep.runner.kv_tier
            cl = getattr(tier, "cluster", None) if tier is not None else None
            if cl is not None and not any(
                    o.runner.kv_tier is tier
                    for orid, o in self.replicas.items()
                    if orid != replica_id
                    and self._health[orid] != REPLICA_FAILED):
                cl.on_owner_death(tier.owner)
        except Exception as e:
            logger.warning("cluster reconciliation for dead replica %s "
                           "failed: %s", replica_id, e)
        self._c_recoveries.inc()
        self._c_recovered.inc(len(moved))
        ms = 1e3 * (time.perf_counter() - t0)
        self.recovery_times_ms.append(ms)
        logger.warning(
            "recovered replica %s without its cooperation: %d stream(s) "
            "rebuilt from the journal and re-queued, %d tier "
            "reservation(s) restored (%.2f ms)",
            replica_id, len(moved), restored, ms)
        return len(moved)

    def reactivate_replica(self, replica_id: str,
                           replica: Optional[EngineReplica] = None) -> None:
        """Return a replica to the placement set.

        A DRAINED replica reactivates in place (its runner kept serving
        state coherently). A FAILED replica's runner is NOT trustworthy —
        its roster still holds ghost rows for streams that already moved —
        so reactivation after FAILED requires a FRESH ``replica`` object
        (same id, new runner); passing none raises."""
        old = self.replicas[replica_id]
        if replica is not None:
            if replica.replica_id != replica_id:
                raise ValueError(
                    f"replacement replica id {replica.replica_id!r} != "
                    f"{replica_id!r}")
            if replica.runner.paged != self.paged or (
                    self.paged
                    and replica.runner.block_size != self.block_size):
                raise ValueError("replacement replica must match the "
                                 "fleet's paged/block-size geometry")
            if self.sla is not None:
                self._check_replica_classes(replica)
            self.replicas[replica_id] = replica
            if self.fault_injector is not None:
                self.fault_injector.attach_replica(replica)
        elif self._health[replica_id] == REPLICA_FAILED:
            raise ValueError(
                f"replica {replica_id} is FAILED: its runner still holds "
                f"the dead roster; reactivate with a fresh replica= "
                f"(same id, new runner)")
        del old
        if self.fault_injector is not None:
            self.fault_injector.revive(replica_id)
        self.replicas[replica_id].reactivate()
        self._fail_streak[replica_id] = 0
        self._retry_after[replica_id] = 0
        self._set_state(replica_id, REPLICA_HEALTHY)

    def add_replica(self, replica: EngineReplica) -> None:
        """Grow the fleet by one replica (serving/autoscaler.py scale-up).
        The replica must match the fleet's paged/block-size geometry and
        carry a fresh id; it joins HEALTHY and takes placements from the
        next ``place_queued``."""
        rid = replica.replica_id
        if rid in self.replicas:
            raise ValueError(f"replica id {rid!r} already registered "
                             f"(reactivate_replica swaps a FAILED one)")
        if replica.runner.paged != self.paged or (
                self.paged and replica.runner.block_size != self.block_size):
            raise ValueError("new replica must match the fleet's "
                             "paged/block-size geometry")
        if self.sla is not None:
            self._check_replica_classes(replica)
        # affinity needs hash visibility on EVERY replica (ctor contract);
        # one opaque allocator degrades the whole fleet to load placement
        if self.prefix_caching and not (
                getattr(replica.runner.allocator, "enable_prefix_caching",
                        False)
                and hasattr(replica.runner.allocator, "hash_to_block")):
            logger.warning("replica %s has no prefix-hash visibility: fleet "
                           "degrades to load placement", rid)
            self.prefix_caching = False
        self.replicas[rid] = replica
        self._fail_streak[rid] = 0
        self._retry_after[rid] = 0
        self._g_state[rid] = self.registry.gauge(
            "serving_replica_state",
            "replica lifecycle: 0 healthy, 1 degraded, 2 failed, 3 retired",
            labels={"replica": rid})
        self._set_state(rid, REPLICA_HEALTHY)
        if self.fault_injector is not None:
            self.fault_injector.attach_replica(replica)
        self._trace_event("add_replica", replica=rid,
                          fleet_size=len(self.replicas))
        logger.info("added replica %s (fleet size %d)", rid,
                    len(self.replicas))

    def remove_replica(self, replica_id: str) -> EngineReplica:
        """Retire a replica for good (autoscaler scale-down). A live replica
        must have been DRAINED first (its streams migrated bit-exactly) and
        hold no unfinished work; a FAILED replica retires as-is (its streams
        already moved via ``recover_replica``). The state gauge is left at
        ``retired`` so the scale-down is visible in the scrape history.
        Returns the removed replica."""
        rep = self.replicas[replica_id]
        if len(self.replicas) <= 1:
            raise ValueError("cannot remove the last replica")
        if self._health[replica_id] != REPLICA_FAILED:
            inflight = [gid for (r, _l), gid in self._local.items()
                        if r == replica_id and not self.requests[gid].done]
            if not rep.draining:
                raise ValueError(
                    f"replica {replica_id} is not draining: call "
                    f"drain_replica() first so its streams migrate")
            if inflight or rep.has_work:
                raise ValueError(
                    f"replica {replica_id} still has live work "
                    f"(in-flight frontend ids {inflight[:8]}); step the "
                    f"router until it drains")
        self._set_state(replica_id, REPLICA_RETIRED)
        del self.replicas[replica_id]
        del self._health[replica_id]
        self._fail_streak.pop(replica_id, None)
        self._retry_after.pop(replica_id, None)
        self._g_state.pop(replica_id, None)
        self._trace_event("remove_replica", replica=replica_id,
                          fleet_size=len(self.replicas))
        logger.info("retired replica %s (fleet size %d)", replica_id,
                    len(self.replicas))
        return rep

    # ------------------------------------------------------------- export
    def stats(self) -> Dict[str, object]:
        per_replica = {}
        for rid, rep in self.replicas.items():
            try:
                a = dict(rep.admission())
            except Exception as e:
                # a dead replica must not take the stats surface with it
                logger.warning("admission probe of replica %s failed: %s",
                               rid, e)
                a = {"replica": rid, "queue_depth": 0, "active_requests": 0,
                     "error": repr(e)}
            a["state"] = self._health[rid]
            per_replica[rid] = a
        depths = [a["queue_depth"] + a["active_requests"]
                  for a in per_replica.values()]
        mean = sum(depths) / max(1, len(depths))
        # the fleet's (first-found) cluster KV store — replicas share one
        cluster_kv = next(
            (cl for cl in (getattr(r.runner.kv_tier, "cluster", None)
                           for r in self.replicas.values())
             if cl is not None), None)
        return {
            "policy": self.policy,
            "prefix_caching": self.prefix_caching,
            "knobs": self.knobs.snapshot(),
            "queue_depth": len(self.queue),
            "requests": self._c_submitted.value,
            "finished": self._c_finished.value,
            "tokens": self._c_tokens.value,
            "placements": self._c_placed.value,
            "affinity_hits": self._c_aff_hits.value,
            "affinity_blocks": self._c_aff_blocks.value,
            "cluster_affinity_hits": self._c_cluster_aff_hits.value,
            "cluster_affinity_blocks": self._c_cluster_aff_blocks.value,
            "affinity_spills": self._c_spills.value,
            "affinity_lost_blocks": self._c_spill_blocks.value,
            "migrations": self._c_migrations.value,
            # max/mean replica load (queue + live rows) — the imbalance
            # number bench publishes as replica_load_imbalance
            "load_imbalance": (max(depths) / mean if mean > 0 else 1.0),
            # supervision / fault tolerance (ISSUE-11)
            "replica_state": dict(self._health),
            "failures": sum(c.value for c in self._c_failures.values()),
            "recoveries": self._c_recoveries.value,
            "recovered_requests": self._c_recovered.value,
            "shed": self._c_shed.value,
            "affinity_unavailable": self._c_aff_unavail.value,
            "recovery_times_ms": [round(t, 3)
                                  for t in self.recovery_times_ms],
            "faults_injected": (self.fault_injector.fired_total
                                if self.fault_injector is not None else 0),
            "replicas": per_replica,
            # fleet-wide content-addressed store (ISSUE-20), when attached
            **({"cluster_kv": cluster_kv.stats()}
               if cluster_kv is not None else {}),
            # disaggregated pools: handoff accounting (remote_prefill only)
            **({"pools": self.pools.stats()}
               if self.pools is not None else {}),
            # overload control plane (ISSUE-13): brown-out state + per-class
            # shed/preempt/defer accounting (absent on classless routers)
            **({"sla": {
                "classes": self.sla.names(),
                "default": self.sla.default,
                "brownout_level": self._brownout_level,
                "brownout_ladder": [f"{k}:{c}" for k, c in self._ladder],
                "brownout_shed": sorted(self._brownout_actions()["shed"]),
                "brownout_capped": sorted(self._brownout_actions()["cap"]),
                "shed_by_class": {c: int(cnt.value) for c, cnt
                                  in sorted(self._c_class_shed.items())},
                "preempted_by_class": {
                    c: int(cnt.value) for c, cnt
                    in sorted(self._c_class_preempt.items())},
                "deferred_by_class": {
                    c: int(cnt.value) for c, cnt
                    in sorted(self._c_class_deferred.items())},
                "queued_by_class": {
                    c: sum(1 for r in self.queue if r.sla_class == c)
                    for c in self.sla.names()},
            }} if self.sla is not None else {}),
        }

    def prometheus_text(self) -> str:
        """One exposition: the router's own series plus every replica's
        (replica-labelled) registry — the label-merging the
        MetricsRegistry(default_labels=) satellite exists for. Repeated
        ``# HELP``/``# TYPE`` headers are dropped (every replica registers
        the same families; a second metadata line for one family is invalid
        exposition and real scrapers reject the whole page)."""
        parts = [self.registry.prometheus_text()]
        parts += [rep.prometheus_text() for rep in self.replicas.values()]
        # regroup by family: the format requires one metadata block and ALL
        # series of a family to be consecutive; headers keep first-seen text
        meta: Dict[str, List[str]] = {}        # family -> header lines
        series: Dict[str, List[str]] = {}      # family -> series lines
        order: List[str] = []

        def family_of(line: str) -> str:
            if line.startswith("#"):
                toks = line.split(None, 3)
                return toks[2] if len(toks) >= 3 else line
            name = line.split("{", 1)[0].split(" ", 1)[0]
            # histogram child series fold into their family
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in meta:
                    return name[: -len(suffix)]
            return name
        for part in parts:
            for line in part.splitlines():
                fam = family_of(line)
                if fam not in meta:
                    meta[fam] = []
                    series[fam] = []
                    order.append(fam)
                if line.startswith("#"):
                    if not any(ln.split(None, 2)[1] == line.split(None, 2)[1]
                               for ln in meta[fam]):
                        meta[fam].append(line)
                else:
                    series[fam].append(line)
        out = [ln for fam in order for ln in meta[fam] + series[fam]]
        return "\n".join(out) + ("\n" if out else "")
