"""Frontend of the scale-out split: prefix-affinity request router.

:class:`PrefixAffinityRouter` owns the arrival queue and places each request
on an :class:`~.engine.EngineReplica`:

- **Prefix-cache affinity**: the router hashes the prompt's leading full
  blocks with the SAME chained content hash the replicas' prefix caches key
  blocks by (engine.prompt_block_hashes), and scores each replica by how many
  leading blocks it already holds (device cache, idle pool, or host-RAM
  tier). The replica holding the longest prefix wins ties — the placement
  that converts block residency into skipped prefill.
- **Load balancing**: among equal-affinity replicas the one with the most KV
  headroom wins, then the shallowest queue — the admission signals
  EngineReplica.admission() exports (the same numbers the SLO monitor and a
  scrape see).
- **Graceful spill**: when the affinity target is saturated
  (``has_headroom`` false), the request places on the best-by-load admitting
  replica instead and the LOST prefix hit is recorded
  (``router_affinity_spills_total`` + lost-block count) — saturation trades
  recompute for latency, visibly.
- **Drain**: ``drain_replica(id)`` evicts the replica's live requests
  through the runner's existing mid-prompt preemption/resume path and
  re-places them (``submit(resume_tokens=...)`` on the target), preserving
  every request's emitted stream exactly across the migration.

The router is synchronous-cooperative: ``step()`` places what the replicas
can admit, then steps every replica with work (one serving wave). An async
server loop wraps ``submit``/``step``; the placement policy has no timing
dependence, so the tests drive it deterministically.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import metrics as metrics_lib
from .engine import EngineReplica, prompt_block_hashes

logger = logging.getLogger("tpu-inference")

__all__ = ["PrefixAffinityRouter", "RouterRequest"]


@dataclass
class RouterRequest:
    """Frontend-side request record: the prompt + serving params, the
    precomputed affinity hash chain, and the placement/emission state the
    router tracks across replicas (a request may migrate)."""

    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    sampling_params: Optional[np.ndarray] = None
    adapter_id: int = 0
    arrival_ts: Optional[float] = None
    hashes: List[bytes] = field(default_factory=list)
    replica: Optional[str] = None        # current placement (None = queued)
    local_id: Optional[int] = None       # runner-side request id
    generated: List[int] = field(default_factory=list)
    done: bool = False
    migrations: int = 0
    affinity_blocks: int = 0             # resident blocks at placement time


class PrefixAffinityRouter:
    """Place requests over N EngineReplicas by prefix affinity + load.

    ``policy``: ``"affinity"`` (default), ``"load"`` (headroom/queue only),
    or ``"random"`` (uniform over admitting replicas — the bench's control
    arm for the affinity-hit comparison).
    """

    def __init__(self, replicas: Sequence[EngineReplica],
                 policy: str = "affinity", seed: int = 0):
        if not replicas:
            raise ValueError("need at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"replica ids must be unique, got {ids}")
        if policy not in ("affinity", "load", "random"):
            raise ValueError(f"unknown placement policy {policy!r}")
        self.replicas: Dict[str, EngineReplica] = {
            r.replica_id: r for r in replicas}
        self.policy = policy
        self._rng = np.random.default_rng(seed)
        paged = {r.runner.paged for r in replicas}
        if len(paged) != 1:
            raise ValueError("replicas must agree on paged vs dense serving")
        self.paged = paged.pop()
        if self.paged:
            sizes = {r.runner.block_size for r in replicas}
            if len(sizes) != 1:
                raise ValueError("replicas must share one pa_block_size "
                                 f"(got {sorted(sizes)})")
            self.block_size = sizes.pop()
        else:
            self.block_size = 0
        self.queue: List[RouterRequest] = []
        self.requests: Dict[int, RouterRequest] = {}
        self._next_id = 0
        # (replica_id, local_id) -> global request id
        self._local: Dict[tuple, int] = {}
        # affinity needs more than a prefix cache being ON: the router must
        # be able to SEE each replica's resident hashes. The native C++
        # allocator keeps its hash table internal, so a fleet on it honestly
        # degrades to load placement (and bench's honesty guard refuses to
        # publish affinity numbers) instead of scoring every replica 0.
        self.prefix_caching = self.paged and all(
            getattr(r.runner.allocator, "enable_prefix_caching", False)
            and hasattr(r.runner.allocator, "hash_to_block")
            for r in replicas)

        reg = metrics_lib.MetricsRegistry()
        self.registry = reg
        self._c_submitted = reg.counter(
            "router_requests_total", "requests accepted by the frontend")
        self._c_placed = reg.counter(
            "router_placements_total", "request placements onto replicas "
            "(migrations re-count)")
        self._c_finished = reg.counter(
            "router_requests_finished_total", "requests fully served")
        self._c_tokens = reg.counter(
            "router_tokens_total", "tokens emitted across all replicas")
        self._c_aff_hits = reg.counter(
            "router_prefix_affinity_hits_total",
            "placements that landed on a replica already holding >=1 "
            "leading prompt block")
        self._c_aff_blocks = reg.counter(
            "router_prefix_affinity_blocks_total",
            "resident leading blocks at placement (skipped prefill, blocks)")
        self._c_spills = reg.counter(
            "router_affinity_spills_total",
            "placements diverted off a saturated affinity target")
        self._c_spill_blocks = reg.counter(
            "router_affinity_lost_blocks_total",
            "resident blocks LOST to spills (recompute bought latency)")
        self._c_migrations = reg.counter(
            "router_migrations_total",
            "requests re-placed by a replica drain")
        self._g_queue = reg.gauge(
            "router_queue_depth", "requests waiting at the frontend")

    # ---------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, sampling_params=None,
               adapter_id: int = 0, arrival_ts: Optional[float] = None) -> int:
        prompt = np.asarray(prompt).astype(np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        req = RouterRequest(
            self._next_id, prompt, max_new_tokens, eos_token_id,
            None if sampling_params is None
            else np.asarray(sampling_params, dtype=np.float32).reshape(-1),
            adapter_id, arrival_ts,
            hashes=(prompt_block_hashes(prompt, self.block_size, adapter_id)
                    if self.paged else []))
        self._next_id += 1
        self.requests[req.request_id] = req
        self.queue.append(req)
        self._c_submitted.inc()
        self._g_queue.set(len(self.queue))
        return req.request_id

    # ------------------------------------------------------------- placement
    def _affinity(self, req: RouterRequest) -> Dict[str, int]:
        return {rid: rep.resident_prefix_blocks(req.hashes)
                for rid, rep in self.replicas.items()
                if not rep.draining}

    def _load_key(self, rep: EngineReplica):
        """Sort key: most KV headroom first, then shallowest queue, then
        fewest live rows — ties broken by id for determinism."""
        a = rep.admission()
        return (-a.get("kv_blocks_free", 0), a["queue_depth"],
                a["active_requests"], rep.replica_id)

    def _choose(self, req: RouterRequest):
        """Returns (replica, affinity_blocks, spilled_from) or None when no
        replica can admit the request right now."""
        # a migrated request refeeds prompt + generated at placement, so its
        # KV footprint is the FULL stream so far, not the prompt alone
        n = len(req.prompt) + len(req.generated)
        admitting = [r for r in self.replicas.values() if r.can_admit(n)]
        if not admitting:
            return None
        if self.policy == "random":
            rep = admitting[int(self._rng.integers(len(admitting)))]
            return rep, rep.resident_prefix_blocks(req.hashes), None
        if self.policy == "load" or not self.prefix_caching:
            rep = min(admitting, key=self._load_key)
            return rep, rep.resident_prefix_blocks(req.hashes), None
        aff = self._affinity(req)
        best_aff = max((aff.get(r.replica_id, 0) for r in admitting),
                       default=0)
        if best_aff > 0:
            targets = [r for r in admitting
                       if aff.get(r.replica_id, 0) == best_aff]
            # affinity target with immediate headroom wins; a saturated
            # target spills to the best-by-load admitting replica
            ready = [r for r in targets if r.has_headroom(n)]
            if ready:
                rep = min(ready, key=self._load_key)
                return rep, best_aff, None
            others = [r for r in admitting if r not in targets]
            ready_others = [r for r in others if r.has_headroom(n)]
            if ready_others:
                rep = min(ready_others, key=self._load_key)
                return rep, aff.get(rep.replica_id, 0), best_aff
            # nobody has immediate headroom: queue on the affinity target
            # (the hit survives the wait)
            rep = min(targets, key=self._load_key)
            return rep, best_aff, None
        rep = min(admitting, key=self._load_key)
        return rep, 0, None

    def place_queued(self) -> int:
        """Place as many queued requests as replicas will admit (FIFO).
        Returns the number placed this call."""
        placed = 0
        remaining: List[RouterRequest] = []
        for req in self.queue:
            choice = self._choose(req)
            if choice is None:
                remaining.append(req)
                continue
            rep, aff_blocks, lost = choice
            self._place(req, rep, aff_blocks, lost)
            placed += 1
        self.queue = remaining
        self._g_queue.set(len(self.queue))
        return placed

    def _place(self, req: RouterRequest, rep: EngineReplica,
               aff_blocks: int, lost: Optional[int]) -> None:
        kw = dict(max_new_tokens=req.max_new_tokens,
                  eos_token_id=req.eos_token_id,
                  adapter_id=req.adapter_id, arrival_ts=req.arrival_ts)
        if req.sampling_params is not None:
            kw["sampling_params"] = req.sampling_params
        if req.generated:
            kw["resume_tokens"] = req.generated
        req.local_id = rep.submit(req.prompt, **kw)
        req.replica = rep.replica_id
        req.affinity_blocks = aff_blocks
        self._local[(rep.replica_id, req.local_id)] = req.request_id
        self._c_placed.inc()
        if aff_blocks > 0:
            self._c_aff_hits.inc()
            self._c_aff_blocks.inc(aff_blocks)
        if lost is not None:
            self._c_spills.inc()
            self._c_spill_blocks.inc(max(0, lost - aff_blocks))

    # ------------------------------------------------------------- serving
    def step(self) -> Dict[int, List[int]]:
        """One serving wave: place what fits, step every replica with work,
        fold each replica's emissions back to frontend request ids."""
        self.place_queued()
        emitted: Dict[int, List[int]] = {}
        for rid, rep in self.replicas.items():
            if not rep.has_work:
                continue
            for local_id, toks in rep.step().items():
                self._fold(rid, local_id, toks, emitted)
        return emitted

    def _fold(self, rid: str, local_id: int, toks: List[int],
              emitted: Dict[int, List[int]]) -> None:
        gid = self._local.get((rid, local_id))
        if gid is None:                     # foreign submit, not ours
            return
        req = self.requests[gid]
        if toks:
            req.generated.extend(toks)
            emitted.setdefault(gid, []).extend(toks)
            self._c_tokens.inc(len(toks))
        rep = self.replicas[rid]
        local = rep.runner.finished.get(local_id)
        if local is not None and not req.done:
            req.done = True
            self._c_finished.inc()

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(r.has_work
                                       for r in self.replicas.values())

    def run_to_completion(self, max_steps: int = 10000) -> Dict[int, List[int]]:
        guard = 0
        while self.has_work:
            self.step()
            guard += 1
            if guard > max_steps:
                raise RuntimeError("router serving did not converge")
        return {rid: req.generated for rid, req in self.requests.items()}

    # ------------------------------------------------------------- lifecycle
    def drain_replica(self, replica_id: str) -> int:
        """Remove a replica from the placement set: its live requests are
        preempted through the runner's mid-prompt preemption/resume path and
        re-queued at the FRONT of the arrival queue (they resume first, with
        their generated tokens carried via ``resume_tokens``). Returns the
        number of requests migrated. The replica object stays registered
        (``reactivate_replica`` re-adds it)."""
        rep = self.replicas[replica_id]
        emitted, evicted = rep.drain()
        # tokens committed by the pipeline flush still belong to the stream
        final: Dict[int, List[int]] = {}
        for local_id, toks in emitted.items():
            self._fold(replica_id, local_id, toks, final)
        migrated = 0
        for r in reversed(evicted):
            gid = self._local.pop((replica_id, r.request_id), None)
            if gid is None:
                continue
            req = self.requests[gid]
            req.replica = None
            req.local_id = None
            req.migrations += 1
            self.queue.insert(0, req)
            migrated += 1
            self._c_migrations.inc()
        self._g_queue.set(len(self.queue))
        logger.info("drained replica %s: %d requests re-queued for migration",
                    replica_id, migrated)
        return migrated

    def reactivate_replica(self, replica_id: str) -> None:
        self.replicas[replica_id].reactivate()

    # ------------------------------------------------------------- export
    def stats(self) -> Dict[str, object]:
        per_replica = {rid: rep.admission()
                       for rid, rep in self.replicas.items()}
        depths = [a["queue_depth"] + a["active_requests"]
                  for a in per_replica.values()]
        mean = sum(depths) / max(1, len(depths))
        return {
            "policy": self.policy,
            "prefix_caching": self.prefix_caching,
            "queue_depth": len(self.queue),
            "requests": self._c_submitted.value,
            "finished": self._c_finished.value,
            "tokens": self._c_tokens.value,
            "placements": self._c_placed.value,
            "affinity_hits": self._c_aff_hits.value,
            "affinity_blocks": self._c_aff_blocks.value,
            "affinity_spills": self._c_spills.value,
            "affinity_lost_blocks": self._c_spill_blocks.value,
            "migrations": self._c_migrations.value,
            # max/mean replica load (queue + live rows) — the imbalance
            # number bench publishes as replica_load_imbalance
            "load_imbalance": (max(depths) / mean if mean > 0 else 1.0),
            "replicas": per_replica,
        }

    def prometheus_text(self) -> str:
        """One exposition: the router's own series plus every replica's
        (replica-labelled) registry — the label-merging the
        MetricsRegistry(default_labels=) satellite exists for. Repeated
        ``# HELP``/``# TYPE`` headers are dropped (every replica registers
        the same families; a second metadata line for one family is invalid
        exposition and real scrapers reject the whole page)."""
        parts = [self.registry.prometheus_text()]
        parts += [rep.prometheus_text() for rep in self.replicas.values()]
        # regroup by family: the format requires one metadata block and ALL
        # series of a family to be consecutive; headers keep first-seen text
        meta: Dict[str, List[str]] = {}        # family -> header lines
        series: Dict[str, List[str]] = {}      # family -> series lines
        order: List[str] = []

        def family_of(line: str) -> str:
            if line.startswith("#"):
                toks = line.split(None, 3)
                return toks[2] if len(toks) >= 3 else line
            name = line.split("{", 1)[0].split(" ", 1)[0]
            # histogram child series fold into their family
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in meta:
                    return name[: -len(suffix)]
            return name
        for part in parts:
            for line in part.splitlines():
                fam = family_of(line)
                if fam not in meta:
                    meta[fam] = []
                    series[fam] = []
                    order.append(fam)
                if line.startswith("#"):
                    if not any(ln.split(None, 2)[1] == line.split(None, 2)[1]
                               for ln in meta[fam]):
                        meta[fam].append(line)
                else:
                    series[fam].append(line)
        out = [ln for fam in order for ln in meta[fam] + series[fam]]
        return "\n".join(out) + ("\n" if out else "")
