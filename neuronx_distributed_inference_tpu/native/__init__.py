"""Native host-runtime bindings (ctypes over native/engine.cpp).

The shared library is compiled on first import with the system toolchain and cached
next to the source (rebuilt when engine.cpp changes). When no compiler is available the
callers fall back to the pure-Python implementations in modules/block_kvcache — the
semantic reference the native engine is tested against (tests/test_native_engine.py).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("tpu-inference")

_SRC = os.path.join(os.path.dirname(__file__), "engine.cpp")


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(os.path.dirname(__file__), f"_engine_{digest}.so")


def _build() -> Optional[str]:
    path = _lib_path()
    if os.path.exists(path):
        return path
    # compile to a process-private temp then rename: atomic against concurrent
    # importers racing on the same cache path
    tmp = f"{path}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, path)
        return path
    except (OSError, subprocess.CalledProcessError) as e:
        logger.warning("native engine build failed (%s); using Python fallback", e)
        if os.path.exists(tmp):
            os.unlink(tmp)
        return None


_lib = None      # None = untried, False = build failed, CDLL = loaded


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None if unavailable.
    A failed build is cached — no repeated compile attempts."""
    global _lib
    if _lib is not None:
        return _lib or None
    path = _build()
    if path is None:
        _lib = False
        return None
    lib = ctypes.CDLL(path)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.engine_create.restype = ctypes.c_void_p
    lib.engine_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.engine_destroy.argtypes = [ctypes.c_void_p]
    lib.engine_num_free.restype = ctypes.c_int
    lib.engine_num_free.argtypes = [ctypes.c_void_p]
    lib.engine_allocate_for_prompt.restype = ctypes.c_int
    lib.engine_allocate_for_prompt.argtypes = [
        ctypes.c_void_p, i32p, ctypes.c_int, i32p, ctypes.POINTER(ctypes.c_int)]
    lib.engine_extend.restype = ctypes.c_int
    lib.engine_extend.argtypes = [ctypes.c_void_p, i32p, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int]
    lib.engine_free_sequence.argtypes = [ctypes.c_void_p, i32p, ctypes.c_int]
    lib.make_slot_mapping.argtypes = [i32p, ctypes.c_int, ctypes.c_int, i32p,
                                      ctypes.c_int, ctypes.c_int, u8p, i32p]
    _lib = lib
    return lib


def available() -> bool:
    return load() is not None


def _as_i32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeBlockAllocator:
    """Drop-in for modules/block_kvcache.BlockAllocator backed by the C++ engine."""

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_caching: bool = False):
        lib = load()
        if lib is None:
            raise RuntimeError("native engine unavailable")
        self._lib = lib
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self._h = lib.engine_create(num_blocks, block_size,
                                    int(enable_prefix_caching))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.engine_destroy(h)
            self._h = None

    @property
    def num_free(self) -> int:
        return self._lib.engine_num_free(self._h)

    def allocate_for_prompt(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        toks = np.ascontiguousarray(tokens, dtype=np.int32)
        out = np.empty((len(toks) // self.block_size + 2,), dtype=np.int32)
        cached = ctypes.c_int(0)
        n = self._lib.engine_allocate_for_prompt(
            self._h, _as_i32p(toks), len(toks), _as_i32p(out),
            ctypes.byref(cached))
        if n < 0:
            raise RuntimeError("out of KV blocks")
        return out[:n].tolist(), int(cached.value)

    def extend(self, blocks: List[int], seq_len: int) -> None:
        need = -(-seq_len // self.block_size)
        cap = max(need, len(blocks)) + 1
        buf = np.empty((cap,), dtype=np.int32)
        buf[: len(blocks)] = blocks
        n = self._lib.engine_extend(self._h, _as_i32p(buf), len(blocks),
                                    seq_len, cap)
        if n == -2:
            raise RuntimeError("output buffer capacity exhausted")
        if n < 0:
            raise RuntimeError("out of KV blocks")
        blocks[:] = buf[:n].tolist()

    def free_sequence(self, blocks: Sequence[int]) -> None:
        arr = np.ascontiguousarray(blocks, dtype=np.int32)
        self._lib.engine_free_sequence(self._h, _as_i32p(arr), len(arr))


def native_make_slot_mapping(block_table: np.ndarray, positions: np.ndarray,
                             steps: int, block_size: int,
                             valid: Optional[np.ndarray] = None) -> np.ndarray:
    """C++ slot-mapping (drop-in for block_kvcache.make_slot_mapping)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native engine unavailable (use get_slot_mapping_fn() "
                           "for transparent fallback)")
    bt = np.ascontiguousarray(block_table, dtype=np.int32)
    pos = np.ascontiguousarray(positions, dtype=np.int32)
    rows, max_blocks = bt.shape
    out = np.empty((rows, steps), dtype=np.int32)
    vptr = None
    if valid is not None:
        varr = np.asarray(valid, dtype=np.uint8)
        if varr.ndim == 1:                   # per-row validity -> per-element
            varr = np.broadcast_to(varr[:, None], (rows, steps))
        varr = np.ascontiguousarray(varr)
        vptr = varr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    lib.make_slot_mapping(_as_i32p(bt), rows, max_blocks, _as_i32p(pos), steps,
                          block_size, vptr, _as_i32p(out))
    return out


def make_block_allocator(num_blocks: int, block_size: int,
                         enable_prefix_caching: bool = False):
    """Native allocator when the toolchain permits; Python fallback otherwise."""
    if available():
        return NativeBlockAllocator(num_blocks, block_size, enable_prefix_caching)
    from ..modules.block_kvcache import BlockAllocator

    return BlockAllocator(num_blocks, block_size, enable_prefix_caching)


def get_slot_mapping_fn():
    """The slot-mapping implementation to use (native or Python fallback) — the
    single dispatch point callers should import."""
    if available():
        return native_make_slot_mapping
    from ..modules.block_kvcache import make_slot_mapping

    return make_slot_mapping
