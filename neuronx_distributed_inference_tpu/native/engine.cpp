// Native host runtime for the paged-KV serving path.
//
// ≈ the reference's native layer: NxDI itself is pure Python and leans on closed
// native deps for its runtime (SURVEY §2.1); the TPU build keeps the device path in
// XLA but implements the host-side hot loops natively:
//  - ref-counted block allocator with chained-hash prefix-cache reuse
//    (≈ modules/block_kvcache.BlockAllocator / the reference's block-KV manager
//    `modules/kvcache/block_kv_cache_manager.py`)
//  - slot-mapping generation for decode chunks (per-step scatter targets,
//    ≈ `block_kv_cache_manager.py:376-431` generate_*_slot_mapping)
//
// Exposed as a C ABI consumed via ctypes (native/__init__.py); the Python
// implementations remain as a fallback and as the semantic reference.

#include <array>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Compact SHA-256 (FIPS 180-4) — the prefix-cache key must be collision-resistant
// (blocks are SHARED across requests; a collision would hand one request another's
// KV content), and using the same construction as the Python reference
// (sha256(prev_digest || tokens)) keeps the two implementations bit-identical.
struct Sha256 {
  static constexpr std::array<uint32_t, 64> K = {
      0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
      0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
      0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
      0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
      0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
      0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
      0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
      0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
      0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
      0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
      0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  static std::array<uint8_t, 32> digest(const uint8_t* data, size_t len) {
    std::array<uint32_t, 8> h = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                                 0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::vector<uint8_t> msg(data, data + len);
    msg.push_back(0x80);
    while (msg.size() % 64 != 56) msg.push_back(0);
    uint64_t bits = static_cast<uint64_t>(len) * 8;
    for (int i = 7; i >= 0; --i) msg.push_back((bits >> (8 * i)) & 0xff);
    for (size_t off = 0; off < msg.size(); off += 64) {
      uint32_t w[64];
      for (int i = 0; i < 16; ++i)
        w[i] = (msg[off + 4 * i] << 24) | (msg[off + 4 * i + 1] << 16) |
               (msg[off + 4 * i + 2] << 8) | msg[off + 4 * i + 3];
      for (int i = 16; i < 64; ++i) {
        uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
      }
      auto v = h;
      for (int i = 0; i < 64; ++i) {
        uint32_t S1 = rotr(v[4], 6) ^ rotr(v[4], 11) ^ rotr(v[4], 25);
        uint32_t ch = (v[4] & v[5]) ^ (~v[4] & v[6]);
        uint32_t t1 = v[7] + S1 + ch + K[i] + w[i];
        uint32_t S0 = rotr(v[0], 2) ^ rotr(v[0], 13) ^ rotr(v[0], 22);
        uint32_t maj = (v[0] & v[1]) ^ (v[0] & v[2]) ^ (v[1] & v[2]);
        uint32_t t2 = S0 + maj;
        v = {t1 + t2, v[0], v[1], v[2], v[3] + t1, v[4], v[5], v[6]};
      }
      for (int i = 0; i < 8; ++i) h[i] += v[i];
    }
    std::array<uint8_t, 32> out;
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = h[i] >> 24;
      out[4 * i + 1] = (h[i] >> 16) & 0xff;
      out[4 * i + 2] = (h[i] >> 8) & 0xff;
      out[4 * i + 3] = h[i] & 0xff;
    }
    return out;
  }
};

using Digest = std::array<uint8_t, 32>;

// sha256(prev_digest || tokens) — identical to the Python BlockAllocator chain
// (prev is empty for the first block, matching Python's b"" seed)
Digest chain_hash(const Digest* prev, const int32_t* tokens, int n) {
  std::vector<uint8_t> buf;
  if (prev != nullptr) buf.insert(buf.end(), prev->begin(), prev->end());
  const auto* bytes = reinterpret_cast<const uint8_t*>(tokens);
  buf.insert(buf.end(), bytes, bytes + static_cast<size_t>(n) * 4);
  return Sha256::digest(buf.data(), buf.size());
}

std::string key_of(const Digest& d) {
  return std::string(reinterpret_cast<const char*>(d.data()), d.size());
}

struct Engine {
  int num_blocks = 0;
  int block_size = 0;
  bool prefix_caching = false;
  std::vector<int32_t> free_list;              // back = next to allocate (lowest id)
  std::vector<int32_t> refcount;               // size num_blocks; 0 = free
  std::unordered_map<std::string, int32_t> hash_to_block;
  std::vector<std::string> block_hash;         // "" = none
  std::vector<uint8_t> block_has_hash;

  explicit Engine(int blocks, int bs, bool pc)
      : num_blocks(blocks), block_size(bs), prefix_caching(pc),
        refcount(blocks, 0), block_hash(blocks), block_has_hash(blocks, 0) {
    free_list.reserve(blocks);
    for (int i = blocks - 1; i >= 0; --i) free_list.push_back(i);
  }

  int alloc_one() {
    if (free_list.empty()) return -1;
    int blk = free_list.back();
    free_list.pop_back();
    refcount[blk] = 1;
    return blk;
  }

  void release_one(int blk) {
    if (--refcount[blk] == 0) {
      if (block_has_hash[blk]) {
        auto it = hash_to_block.find(block_hash[blk]);
        if (it != hash_to_block.end() && it->second == blk) hash_to_block.erase(it);
        block_has_hash[blk] = 0;
      }
      free_list.push_back(blk);
    }
  }
};

}  // namespace

extern "C" {

void* engine_create(int num_blocks, int block_size, int enable_prefix_caching) {
  return new Engine(num_blocks, block_size, enable_prefix_caching != 0);
}

void engine_destroy(void* h) { delete static_cast<Engine*>(h); }

int engine_num_free(void* h) {
  return static_cast<int>(static_cast<Engine*>(h)->free_list.size());
}

// Allocate blocks covering `n` prompt tokens (+ the next token's slot).
// out_blocks must hold ceil(n/bs)+1 entries. Returns the block count, and writes the
// number of prefix-cached tokens to *out_cached. Returns -1 when out of blocks (any
// blocks taken so far are rolled back).
int engine_allocate_for_prompt(void* h, const int32_t* tokens, int n,
                               int32_t* out_blocks, int* out_cached) {
  auto* e = static_cast<Engine*>(h);
  const int bs = e->block_size;
  const int n_full = n / bs;
  int count = 0, cached = 0;
  Digest prev{};
  bool have_prev = false;
  bool reusing = e->prefix_caching;
  for (int i = 0; i < n_full; ++i) {
    Digest hh = chain_hash(have_prev ? &prev : nullptr, tokens + i * bs, bs);
    prev = hh;
    have_prev = true;
    std::string kk = key_of(hh);
    if (reusing) {
      auto it = e->hash_to_block.find(kk);
      if (it != e->hash_to_block.end()) {
        e->refcount[it->second]++;
        out_blocks[count++] = it->second;
        cached += bs;
        continue;
      }
    }
    reusing = false;  // first miss ends the shared prefix
    int blk = e->alloc_one();
    if (blk < 0) {
      for (int j = 0; j < count; ++j) e->release_one(out_blocks[j]);
      return -1;
    }
    if (e->prefix_caching) {
      e->hash_to_block[kk] = blk;
      e->block_hash[blk] = kk;
      e->block_has_hash[blk] = 1;
    }
    out_blocks[count++] = blk;
  }
  // trailing partial block (or next-token room) is always private
  if (n - n_full * bs > 0 || n_full == count) {
    int blk = e->alloc_one();
    if (blk < 0) {
      for (int j = 0; j < count; ++j) e->release_one(out_blocks[j]);
      return -1;
    }
    out_blocks[count++] = blk;
  }
  *out_cached = cached;
  return count;
}

// Ensure blocks cover [0, seq_len); appends into out_blocks (capacity max_out).
// Returns the new count or -1 on exhaustion (appended blocks rolled back).
// Returns the new block count, -1 when the pool is out of free blocks, or -2 when
// the caller's `blocks` buffer capacity (max_out) is exhausted before seq_len is
// covered. Either failure rolls back blocks allocated by this call.
int engine_extend(void* h, int32_t* blocks, int n_in, int seq_len, int max_out) {
  auto* e = static_cast<Engine*>(h);
  int count = n_in;
  while (count * e->block_size < seq_len) {
    int rc = (count < max_out) ? -1 : -2;
    int blk = (count < max_out) ? e->alloc_one() : -1;
    if (blk < 0) {
      for (int j = n_in; j < count; ++j) e->release_one(blocks[j]);
      return rc;
    }
    blocks[count++] = blk;
  }
  return count;
}

void engine_free_sequence(void* h, const int32_t* blocks, int n) {
  auto* e = static_cast<Engine*>(h);
  for (int i = 0; i < n; ++i) e->release_one(blocks[i]);
}

// Slot mapping: for each of `rows` sequences and `steps` token positions, the flat
// cache slot written: block_table[row][pos/bs]*bs + pos%bs, or -1 when dropped
// (position beyond the table, or valid[row*steps+j] == 0). valid is a per-element
// (rows, steps) mask or null. out is (rows, steps) int32, row-major.
void make_slot_mapping(const int32_t* block_table, int rows, int max_blocks,
                       const int32_t* positions, int steps, int block_size,
                       const uint8_t* valid, int32_t* out) {
  for (int r = 0; r < rows; ++r) {
    const int32_t* bt = block_table + static_cast<int64_t>(r) * max_blocks;
    for (int j = 0; j < steps; ++j) {
      if (valid != nullptr && !valid[r * steps + j]) {
        out[r * steps + j] = -1;
        continue;
      }
      int pos = positions[r] + j;
      int bi = pos / block_size;
      out[r * steps + j] =
          (bi < max_blocks) ? bt[bi] * block_size + pos % block_size : -1;
    }
  }
}

}  // extern "C"
