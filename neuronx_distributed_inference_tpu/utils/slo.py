"""Rolling-window SLO evaluation over the serving telemetry.

An ``SLOConfig`` declares targets (TTFT/TPOT/queue p99 ceilings, a spec-
acceptance floor, a KV-headroom floor, a preemption-rate ceiling); an
``SLOMonitor`` evaluates them over the last ``window_s`` seconds of the
``ServingTelemetry`` request records plus the live registry gauges, exposes
the verdict as a health gauge (``serving_slo_healthy``) + a violations
counter, and logs every violation as ONE structured JSON line — the shape a
per-replica health exporter (ROADMAP open item 4: the engine/frontend split's
router ingests exactly these signals) scrapes.

Config strings (the CLI's ``--slo`` flag) are ``key=value`` pairs:

    --slo "ttft_p99_ms=500,queue_p99_ms=200,min_accept_mean=1.5,window_s=30"

Unset targets are simply not evaluated — an empty config is healthy by
definition.

Per-SLA-class targets (ISSUE-13): a dotted key scopes a LATENCY target to
one class — evaluated over only that class's samples, violated as
``<class>.<target>``, offenders carrying the class label::

    --slo "ttft_p99_ms=500,interactive.ttft_p99_ms=150,batch.tpot_p99_ms=80"

Requests are classed by the ``sla_class`` their telemetry arrival recorded
(runner ``submit(sla_class=)`` — serving/sla.py); a class target over a run
with no classed requests measures nothing and renders no verdict, exactly
like any other unmeasured target.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger("tpu-inference")

__all__ = ["SLOConfig", "SLOMonitor", "SLOReport"]


@dataclasses.dataclass
class SLOConfig:
    """Serving-level objectives; ``None`` disables a target."""

    ttft_p99_ms: Optional[float] = None
    ttft_p50_ms: Optional[float] = None
    tpot_p99_ms: Optional[float] = None
    queue_p99_ms: Optional[float] = None
    # floor on mean committed tokens/row/iteration (spec serving)
    min_accept_mean: Optional[float] = None
    # floor on free-KV-block fraction (paged serving)
    min_kv_headroom: Optional[float] = None
    # ceiling on preemptions per minute over the window
    max_preemptions_per_min: Optional[float] = None
    window_s: float = 60.0
    # how many offending requests a violated latency target NAMES in the
    # slo_violation line (worst-k by sample value, with trace ids — the
    # jump-off into scripts/explain_request.py)
    worst_k: int = 3
    # per-SLA-class latency targets: {class: {target_name: ceiling_ms}} —
    # evaluated over that class's samples only (ISSUE-13 satellite)
    class_targets: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)

    _NON_TARGETS = ("window_s", "worst_k", "class_targets")
    # targets a dotted <class>.<key> entry may scope (latency-sample-backed)
    _CLASS_TARGET_KEYS = ("ttft_p99_ms", "ttft_p50_ms", "tpot_p99_ms",
                          "queue_p99_ms")

    @classmethod
    def parse(cls, spec: str) -> "SLOConfig":
        """Parse the CLI's ``key=value[,key=value...]`` form; dotted keys
        (``interactive.ttft_p99_ms=150``) scope a latency target to one SLA
        class. Unknown keys raise (a typo'd SLO must not silently pass
        forever)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {}
        class_targets: Dict[str, Dict[str, float]] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"SLO spec entry {part!r} is not key=value")
            k, v = part.split("=", 1)
            k = k.strip()
            if "." in k:
                cls_name, _, target = k.partition(".")
                if target not in cls._CLASS_TARGET_KEYS:
                    raise ValueError(
                        f"unknown per-class SLO target {target!r} in {k!r} "
                        f"(known: {list(cls._CLASS_TARGET_KEYS)})")
                class_targets.setdefault(cls_name, {})[target] = float(v)
                continue
            if k not in fields:
                raise ValueError(f"unknown SLO target {k!r} "
                                 f"(known: {sorted(fields)})")
            kw[k] = int(v) if k == "worst_k" else float(v)
        if class_targets:
            kw["class_targets"] = class_targets
        return cls(**kw)

    def targets(self) -> Dict[str, float]:
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)
                if f.name not in self._NON_TARGETS
                and getattr(self, f.name) is not None}


@dataclasses.dataclass
class SLOReport:
    healthy: bool
    violations: List[str]
    values: Dict[str, Optional[float]]      # measured value per target
    window_s: float
    window_requests: int
    # per violated LATENCY target: the worst-k offending requests
    # [{request_id, trace_id, sla_class, value_ms}, ...] — the aggregate
    # percentile, made actionable (feed the trace_id to
    # scripts/explain_request.py; the class label says WHOSE tier blew it)
    offenders: Dict[str, List[dict]] = dataclasses.field(default_factory=dict)
    # measured value per configured per-class target: {class: {target: v}}
    class_values: Dict[str, Dict[str, Optional[float]]] = dataclasses.field(
        default_factory=dict)


def _p(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals), q))


class SLOMonitor:
    """Evaluates an SLOConfig against a live ServingTelemetry.

    One monitor per runner/replica; call ``evaluate()`` periodically (the
    CLI's ``--slo`` wiring evaluates every ``--slo-interval`` serving steps).
    State between calls is only the preemption-counter baseline (for the
    rate target) — everything else reads the telemetry fresh.
    """

    def __init__(self, telemetry, config: SLOConfig):
        self.telemetry = telemetry
        self.config = config
        reg = telemetry.registry
        self._g_healthy = reg.gauge(
            "serving_slo_healthy",
            "1 while every configured SLO target holds, else 0")
        self._c_violations = reg.counter(
            "serving_slo_violations_total",
            "SLO target violations observed across evaluations")
        self._g_healthy.set(1)
        self._last_eval_t: Optional[float] = None
        self._last_preempt = self._preemptions()

    def _preemptions(self) -> int:
        c = self.telemetry.registry.get("serving_preemptions_total")
        return int(c.value) if c is not None else 0

    # ------------------------------------------------------------------ eval
    def evaluate(self, now: Optional[float] = None) -> SLOReport:
        """One rolling-window evaluation; sets the health gauge, counts and
        logs violations (one structured JSON log line per unhealthy eval)."""
        tel = self.telemetry
        cfg = self.config
        now = (time.perf_counter() if now is None else now) - tel._t0
        lo = now - cfg.window_s

        # samples carry their request id (worst-k offender naming) and SLA
        # class (per-class targets + offender attribution, serving/sla.py)
        ttft_s, tpot_s, queue_s = [], [], []
        n_win = 0
        for rid, r in tel.requests.items():
            ft, lt = r["first_token_ts"], r["last_token_ts"]
            live = r["finish_ts"] is None
            cls = r.get("sla_class")
            if ft is not None and ft >= lo:
                n_win += 1
                ttft_s.append((1e3 * (ft - r["arrival_ts"]), rid, cls))
            elif ft is None and live and r["arrival_ts"] <= now:
                # CENSORED sample: a live request with no first token yet
                # contributes its AGE as a TTFT lower bound — a wedged
                # replica (requests arrive, nothing is produced) must flag
                # the ceiling, not read as "nothing measured, no verdict"
                n_win += 1
                ttft_s.append((1e3 * (now - r["arrival_ts"]), rid, cls))
            # TPOT windows on ACTIVITY (last token in window), not on the
            # first token: a generation longer than window_s would otherwise
            # drop out of the window while still degrading
            if ft is not None and lt is not None and lt >= lo \
                    and r["tokens"] > 1:
                tpot_s.append((1e3 * (lt - ft) / (r["tokens"] - 1), rid, cls))
            if r["placed_ts"] is not None and r["placed_ts"] >= lo:
                queue_s.append((1e3 * (r["placed_ts"] - r["arrival_ts"]),
                                rid, cls))
            elif r["placed_ts"] is None and live and r["arrival_ts"] <= now:
                # censored queue-wait for requests still waiting on a slot
                queue_s.append((1e3 * (now - r["arrival_ts"]), rid, cls))
        ttft = [v for v, _, _ in ttft_s]
        tpot = [v for v, _, _ in tpot_s]
        queue = [v for v, _, _ in queue_s]

        reg = tel.registry
        values: Dict[str, Optional[float]] = {
            "ttft_p99_ms": _p(ttft, 99), "ttft_p50_ms": _p(ttft, 50),
            "tpot_p99_ms": _p(tpot, 99), "queue_p99_ms": _p(queue, 99),
        }
        # spec acceptance over the whole registry histogram (cumulative —
        # a windowed acceptance needs the device carry's per-window deltas;
        # the floor target is about sustained regime shifts, where the
        # cumulative mean converges to the recent mean)
        hist = reg.get("serving_spec_acceptance_tokens")
        if hist is not None and hist.count:
            from .metrics import acceptance_mean

            values["min_accept_mean"] = acceptance_mean(hist.counts[:-1])
        else:
            values["min_accept_mean"] = None
        free = reg.get("serving_kv_blocks_free")
        used = reg.get("serving_kv_blocks_used")
        if free is not None and used is not None and free.updated:
            total = free.value + used.value
            values["min_kv_headroom"] = (free.value / total) if total else None
        else:
            values["min_kv_headroom"] = None
        dt = None if self._last_eval_t is None else max(1e-9,
                                                        now - self._last_eval_t)
        preempt = self._preemptions()
        if dt is not None:
            values["max_preemptions_per_min"] = \
                60.0 * (preempt - self._last_preempt) / dt
        else:
            values["max_preemptions_per_min"] = None
        self._last_eval_t = now
        self._last_preempt = preempt

        violations: List[str] = []
        samples_by_target = {"ttft_p99_ms": ttft_s, "ttft_p50_ms": ttft_s,
                             "tpot_p99_ms": tpot_s, "queue_p99_ms": queue_s}
        offenders: Dict[str, List[dict]] = {}

        def _name_offenders(key: str, samples: List[tuple]) -> None:
            """The worst-k requests behind a blown percentile — named, with
            trace ids AND class labels, so the violation is actionable
            (scripts/explain_request.py takes it from here)."""
            worst = sorted(samples, key=lambda s: s[0],
                           reverse=True)[: max(0, cfg.worst_k)]
            offenders[key] = [
                {"request_id": rid,
                 "trace_id": tel.requests[rid].get("trace_id"),
                 "sla_class": s_cls,
                 "value_ms": round(val, 3)}
                for val, rid, s_cls in worst]

        for name, target in cfg.targets().items():
            v = values.get(name)
            if v is None:
                continue                       # nothing measured: no verdict
            if name.startswith("min_"):
                if v < target:
                    violations.append(f"{name}: {v:.4g} < floor {target:.4g}")
            elif v > target:
                violations.append(f"{name}: {v:.4g} > ceiling {target:.4g}")
                samples = samples_by_target.get(name)
                if samples:
                    _name_offenders(name, samples)

        # per-SLA-class targets (ISSUE-13): each evaluates over ONLY its
        # class's samples; violations and offenders carry the class name,
        # so the monitor can finally say WHOSE tier degraded instead of
        # judging the fleet as one blob
        class_values: Dict[str, Dict[str, Optional[float]]] = {}
        for cls_name, targets in cfg.class_targets.items():
            cvals: Dict[str, Optional[float]] = {}
            for name, target in targets.items():
                samples = [s for s in samples_by_target.get(name, ())
                           if s[2] == cls_name]
                q = 50 if name.endswith("p50_ms") else 99
                v = _p([s[0] for s in samples], q)
                cvals[name] = v
                if v is None:
                    continue                   # nothing measured: no verdict
                if v > target:
                    violations.append(
                        f"{cls_name}.{name}: {v:.4g} > ceiling {target:.4g}")
                    if samples:
                        _name_offenders(f"{cls_name}.{name}", samples)
            class_values[cls_name] = cvals

        healthy = not violations
        self._g_healthy.set(1 if healthy else 0)
        if violations:
            self._c_violations.inc(len(violations))
            # ONE structured line per unhealthy evaluation — log scrapers
            # key on "slo_violation"
            logger.warning("slo_violation %s", json.dumps({
                "violations": violations, "window_s": cfg.window_s,
                "window_requests": n_win,
                "offenders": offenders,
                "values": {k: v for k, v in values.items()
                           if v is not None},
                **({"class_values": {
                    c: {k: v for k, v in cv.items() if v is not None}
                    for c, cv in class_values.items()}}
                   if class_values else {})}))
        return SLOReport(healthy=healthy, violations=violations,
                         values=values, window_s=cfg.window_s,
                         window_requests=n_win, offenders=offenders,
                         class_values=class_values)
