"""HuggingFace-style generation adapter.

≈ reference `utils/hf_adapter.py` (`HuggingFaceGenerationAdapter` :104, `_sample` loop
:139-257). The TPU application's own `generate` already runs the on-device sampling
loop; this adapter provides the familiar HF calling convention on top — torch/np tensor
inputs, `GenerationConfig`-style kwargs, tokenizer round-trips — so reference users can
swap in without changing their driver code.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops.sampling import prepare_sampling_params


class HuggingFaceGenerationAdapter:
    """Wraps a TpuModelForCausalLM with an HF-`generate`-shaped API."""

    def __init__(self, app, tokenizer=None):
        self.app = app
        self.tokenizer = tokenizer
        self.config = app.config

    def generate(
        self,
        input_ids=None,
        attention_mask=None,
        max_new_tokens: int = 32,
        max_length: Optional[int] = None,
        do_sample: bool = False,
        top_k: int = 50,
        top_p: float = 1.0,
        temperature: float = 1.0,
        eos_token_id: Optional[int] = None,
        pad_token_id: Optional[int] = None,
        seed: int = 0,
        **ignored,
    ):
        """HF-compatible subset: returns full sequences (prompt + generated) shaped like
        `transformers` `generate` with right padding."""
        is_torch = _is_torch(input_ids)
        ids = _to_numpy(input_ids)
        mask = _to_numpy(attention_mask) if attention_mask is not None else None
        if max_length is not None:
            max_new_tokens = max_length - ids.shape[1]
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

        if eos_token_id is None:
            # default from tokenizer / model config, like HF generate
            if self.tokenizer is not None:
                eos_token_id = getattr(self.tokenizer, "eos_token_id", None)
            if eos_token_id is None:
                eos_token_id = getattr(self.config, "eos_token_id", None)
            if isinstance(eos_token_id, (list, tuple)):
                eos_token_id = eos_token_id[0] if eos_token_id else None

        batch = ids.shape[0]
        if do_sample:
            sampling_params = prepare_sampling_params(
                batch, top_k=top_k, top_p=top_p, temperature=temperature)
        else:
            sampling_params = prepare_sampling_params(batch)  # greedy

        out = self.app.generate(
            ids, attention_mask=mask, max_new_tokens=max_new_tokens,
            sampling_params=sampling_params,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id or 0, seed=seed)
        sequences = out.sequences
        if is_torch:
            import torch

            sequences = torch.tensor(sequences, dtype=torch.long)
        return sequences

    def __call__(self, *args, **kwargs):
        return self.generate(*args, **kwargs)

    def generate_assisted(self, input_ids, assistant_model,
                          speculation_length: int = 5, attention_mask=None,
                          max_new_tokens: int = 32, eos_token_id=None,
                          pad_token_id: Optional[int] = None, seed: int = 0,
                          **ignored):
        """HF assisted-decoding analog (≈ reference `_assisted_decoding` routing,
        `utils/hf_adapter.py:494-933`). ``assistant_model`` selects the path:

        - a ``TpuModelForCausalLM`` draft -> fused draft-target speculation
          (≈ `_fused_assisted_decoding` :494);
        - a ``MedusaModel`` -> Medusa tree verify (≈ the Medusa loop :798-925);
        - an ``EagleSpeculativeModel`` / ``Eagle3SpeculativeModel`` -> EAGLE
          hidden-conditioned speculation (chain / dynamic tree).

        Greedy; returns full sequences like `generate`."""
        from ..runtime.eagle import EagleSpeculativeModel
        from ..runtime.eagle3 import Eagle3SpeculativeModel
        from ..runtime.medusa import MedusaModel
        from ..runtime.speculation import FusedSpeculativeModel

        is_torch = _is_torch(input_ids)
        ids = _to_numpy(input_ids)
        mask = _to_numpy(attention_mask) if attention_mask is not None else None
        common = dict(attention_mask=mask, max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id, pad_token_id=pad_token_id or 0)

        if isinstance(assistant_model,
                      (MedusaModel, EagleSpeculativeModel, Eagle3SpeculativeModel,
                       FusedSpeculativeModel)):
            out = assistant_model.generate(ids, **common)
        else:
            key = (id(assistant_model), speculation_length)
            if getattr(self, "_spec_cache_key", None) != key:
                self._spec_model = FusedSpeculativeModel(
                    self.app, assistant_model, speculation_length, greedy=True)
                self._spec_cache_key = key
            out = self._spec_model.generate(ids, seed=seed, **common)
        sequences = out.sequences
        if is_torch:
            import torch

            sequences = torch.tensor(sequences, dtype=torch.long)
        return sequences

    def generate_with_processors(self, input_ids, logits_processor,
                                 attention_mask=None, max_new_tokens: int = 32,
                                 do_sample: bool = False,
                                 eos_token_id: Optional[int] = None,
                                 pad_token_id: int = 0, seed: int = 0):
        """HF logits-processor path (≈ reference `_sample`'s processor handling,
        `utils/hf_adapter.py:139-257`): a host-driven token-by-token loop — each
        step's logits come to the host, ``logits_processor`` (an HF
        LogitsProcessorList or any callable(ids, scores) -> scores, torch tensors)
        rewrites them, and the host-chosen token feeds the next device step.

        This is the SLOW path (one device dispatch per token); the on-device
        sampling loop bypasses processors by design, exactly like the reference's
        on-device-sampling mode."""
        import torch

        from ..modules import autobucketing

        app = self.app
        is_torch = _is_torch(input_ids)
        ids = _to_numpy(input_ids)
        b = ids.shape[0]
        # prefill via the normal path (1 token, with logits); the sampled token is
        # discarded — we re-choose from the processed logits (its KV is never
        # written, so substituting is safe)
        out = app.generate(ids, attention_mask=_to_numpy(attention_mask)
                           if attention_mask is not None else None,
                           max_new_tokens=1, return_logits=True, seed=seed)
        positions = np.asarray(
            (_to_numpy(attention_mask).sum(axis=1)
             if attention_mask is not None
             else np.full((b,), ids.shape[1])), dtype=np.int32)

        def choose(hist, scores):
            t_scores = torch.tensor(scores, dtype=torch.float32)
            t_scores = logits_processor(torch.tensor(hist, dtype=torch.long),
                                        t_scores)
            if do_sample:
                probs = torch.softmax(t_scores, dim=-1)
                return torch.multinomial(probs, 1)[:, 0].numpy().astype(np.int32)
            return t_scores.argmax(dim=-1).numpy().astype(np.int32)

        from ..ops.sampling import prepare_sampling_params
        import jax

        sp = prepare_sampling_params(app.tpu_config.max_batch_size)
        key = jax.random.PRNGKey(seed)
        hist = ids.copy()
        tok = choose(hist, out.logits[0])
        hist = np.concatenate([hist, tok[:, None]], axis=1)
        done = np.zeros((b,), dtype=bool)
        if eos_token_id is not None:
            done |= tok == eos_token_id

        compiled_b = app.tpu_config.max_batch_size
        for _ in range(max_new_tokens - 1):
            if done.all():
                break
            max_pos = int(positions.max())
            bucket = autobucketing.select_bucket(app.tkg_buckets, max_pos + 1)
            tok_full = np.zeros((compiled_b,), dtype=np.int32)
            tok_full[:b] = tok
            pos_full = np.zeros((compiled_b,), dtype=np.int32)
            pos_full[:b] = positions
            key, sub = jax.random.split(key)
            _, step_logits, app.kv_cache = app._decode_step(
                app.params, tok_full, pos_full, app.kv_cache, sp, sub,
                decode_bucket=bucket, num_steps=1, with_logits=True, greedy=True)
            scores = np.asarray(step_logits[0])[:b]
            tok = choose(hist, scores)
            tok = np.where(done, pad_token_id, tok).astype(np.int32)
            hist = np.concatenate([hist, tok[:, None]], axis=1)
            positions = positions + 1
            if eos_token_id is not None:
                done |= tok == eos_token_id
        if is_torch:
            return torch.tensor(hist, dtype=torch.long)
        return hist

    def generate_text(self, prompts, max_new_tokens: int = 64, **kwargs):
        """Tokenizer-in, strings-out convenience."""
        if self.tokenizer is None:
            raise ValueError("construct the adapter with a tokenizer to use "
                             "generate_text")
        enc = self.tokenizer(list(prompts), return_tensors="np", padding=True)
        seqs = self.generate(enc["input_ids"], attention_mask=enc["attention_mask"],
                             max_new_tokens=max_new_tokens, **kwargs)
        return self.tokenizer.batch_decode(np.asarray(seqs), skip_special_tokens=True)


def _is_torch(x) -> bool:
    return type(x).__module__.startswith("torch")


def _to_numpy(x) -> np.ndarray:
    if _is_torch(x):
        return x.detach().cpu().numpy()
    return np.asarray(x)
