"""Device profiling via jax.profiler (≈ reference `utils/profiling.py:33-121`, which
shells out to `neuron-profile capture` on a NEFF; on TPU the XLA/PJRT stack exposes the
same capability natively through jax.profiler traces viewable in TensorBoard /
Perfetto, plus XLA HLO dumps via XLA_FLAGS=--xla_dump_to)."""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Callable, Dict, Mapping, Optional, Sequence

import jax

logger = logging.getLogger("tpu-inference")


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture a device trace for the enclosed block (TensorBoard `logdir`)."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def profile_callable(fn: Callable, *args, logdir: str = "/tmp/tpu_profile",
                     warmup: int = 1, iters: int = 3, **kwargs):
    """Profile ``fn(*args, **kwargs)``: warm (compile), then trace ``iters`` runs.

    Returns (last_result, wall_seconds_per_iter). ≈ the reference's profile-largest-
    bucket flow (`utils/profiling.py:66-121`) without the NEFF bookkeeping.

    ``iters`` must be >= 1 (``iters=0`` used to return an UNBOUND result and
    a meaningless time) and ``warmup`` >= 1 is required for an honest
    per-iteration number: the first call compiles, so ``warmup=0`` folds
    compile time into the reported wall time — allowed (cold-start studies
    measure exactly that) but warned, never silent."""
    if iters < 1:
        raise ValueError(f"profile_callable needs iters >= 1 (got {iters}) — "
                         f"0 iterations has no result or per-iter time")
    if warmup < 0:
        raise ValueError(f"warmup must be >= 0 (got {warmup})")
    if warmup == 0:
        logger.warning(
            "profile_callable(warmup=0): the first traced call compiles, so "
            "the reported per-iter wall time includes compile time")
    for _ in range(warmup):
        result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    t0 = time.perf_counter()
    with trace(logdir):
        for _ in range(iters):
            result = fn(*args, **kwargs)
            jax.block_until_ready(result)
    return result, (time.perf_counter() - t0) / iters


def enable_hlo_dump(dump_dir: str) -> None:
    """Ask XLA to dump HLO for every subsequent compile (≈ `--hlo-debug` metadata,
    `inference_demo.py:383-388`). Must run before the first jit compilation."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_dump_to" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} --xla_dump_to={dump_dir}".strip()


def annotate(name: str):
    """Named trace span (shows up in the profiler timeline)."""
    return jax.profiler.TraceAnnotation(name)


def _iter_xplane_events(logdir: str, plane_substr: str):
    """Yield ``(event_name, duration_ms)`` for every event in the trace's
    xplane dumps whose plane name matches ``plane_substr`` (case-insensitive;
    "" = every plane). Yields nothing when the protobuf stack or the trace is
    absent — callers treat "no events" as None, never as 0."""
    import glob as _glob

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        return
    for p in _glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True):
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if plane_substr and plane_substr.lower() not in plane.name.lower():
                continue
            md = plane.event_metadata
            for line in plane.lines:
                for ev in line.events:
                    yield md[ev.metadata_id].name, ev.duration_ps / 1e9


def device_time_ms(logdir: str, name_substr: str,
                   plane_substr: str = "tpu") -> Optional[float]:
    """Sum the ON-DEVICE duration of top-level executable events whose name
    contains ``name_substr`` in the trace under ``logdir``.

    Parses the jax.profiler xplane output directly (the TPU plane's per-program
    events, e.g. ``jit__prefill``). This is the event-timed device latency the
    bench reports next to wall time — on tunneled environments wall time is
    dominated by dispatch round-trips that local PJRT serving does not pay.
    ``plane_substr`` filters planes case-insensitively (default the TPU device
    plane; pass "" to scan every plane — e.g. the ``/host:CPU`` plane on the
    CPU backend, which is how tests/test_profiling.py exercises this parser
    without accelerator hardware). Returns None when no trace/plane/event is
    found."""
    total = 0.0
    found = False
    for name, dur_ms in _iter_xplane_events(logdir, plane_substr):
        if name_substr in name:
            total += dur_ms
            found = True
    return total if found else None


def device_time_by_substr(logdir: str,
                          names: Mapping[str, Sequence[str]],
                          plane_substr: str = "tpu"
                          ) -> Dict[str, Optional[float]]:
    """Per-key on-device time over ONE xplane walk: ``names`` maps each
    output key (e.g. a serving dispatch kind) to the event-name substrings
    that attribute to it (e.g. the jitted step-fn names — ``_decode`` matches
    the compiled program ``jit__decode``). A key whose substrings match no
    event reports None (distinguishable from a measured 0). Substring sets
    may overlap — each key sums independently, so overlapping keys double-
    COUNT, not double-report (documented for the insert family, where every
    variant is an insert window)."""
    totals: Dict[str, float] = {}
    for name, dur_ms in _iter_xplane_events(logdir, plane_substr):
        for key, subs in names.items():
            if any(s in name for s in subs):
                totals[key] = totals.get(key, 0.0) + dur_ms
    return {key: totals.get(key) for key in names}
