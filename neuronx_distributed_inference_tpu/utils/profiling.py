"""Device profiling via jax.profiler (≈ reference `utils/profiling.py:33-121`, which
shells out to `neuron-profile capture` on a NEFF; on TPU the XLA/PJRT stack exposes the
same capability natively through jax.profiler traces viewable in TensorBoard /
Perfetto, plus XLA HLO dumps via XLA_FLAGS=--xla_dump_to)."""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture a device trace for the enclosed block (TensorBoard `logdir`)."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def profile_callable(fn: Callable, *args, logdir: str = "/tmp/tpu_profile",
                     warmup: int = 1, iters: int = 3, **kwargs):
    """Profile ``fn(*args, **kwargs)``: warm (compile), then trace ``iters`` runs.

    Returns (last_result, wall_seconds_per_iter). ≈ the reference's profile-largest-
    bucket flow (`utils/profiling.py:66-121`) without the NEFF bookkeeping."""
    for _ in range(warmup):
        result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    t0 = time.perf_counter()
    with trace(logdir):
        for _ in range(iters):
            result = fn(*args, **kwargs)
            jax.block_until_ready(result)
    return result, (time.perf_counter() - t0) / max(iters, 1)


def enable_hlo_dump(dump_dir: str) -> None:
    """Ask XLA to dump HLO for every subsequent compile (≈ `--hlo-debug` metadata,
    `inference_demo.py:383-388`). Must run before the first jit compilation."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_dump_to" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} --xla_dump_to={dump_dir}".strip()


def annotate(name: str):
    """Named trace span (shows up in the profiler timeline)."""
    return jax.profiler.TraceAnnotation(name)


def device_time_ms(logdir: str, name_substr: str,
                   plane_substr: str = "tpu") -> Optional[float]:
    """Sum the ON-DEVICE duration of top-level executable events whose name
    contains ``name_substr`` in the trace under ``logdir``.

    Parses the jax.profiler xplane output directly (the TPU plane's per-program
    events, e.g. ``jit__prefill``). This is the event-timed device latency the
    bench reports next to wall time — on tunneled environments wall time is
    dominated by dispatch round-trips that local PJRT serving does not pay.
    ``plane_substr`` filters planes case-insensitively (default the TPU device
    plane; pass "" to scan every plane — e.g. the ``/host:CPU`` plane on the
    CPU backend, which is how tests/test_profiling.py exercises this parser
    without accelerator hardware). Returns None when no trace/plane/event is
    found."""
    import glob as _glob

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        return None
    total = 0.0
    found = False
    for p in _glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True):
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if plane_substr and plane_substr.lower() not in plane.name.lower():
                continue
            md = plane.event_metadata
            for line in plane.lines:
                for ev in line.events:
                    if name_substr in md[ev.metadata_id].name:
                        total += ev.duration_ps / 1e9   # ps -> ms
                        found = True
    return total if found else None
