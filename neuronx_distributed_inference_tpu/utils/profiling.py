"""Device profiling via jax.profiler (≈ reference `utils/profiling.py:33-121`, which
shells out to `neuron-profile capture` on a NEFF; on TPU the XLA/PJRT stack exposes the
same capability natively through jax.profiler traces viewable in TensorBoard /
Perfetto, plus XLA HLO dumps via XLA_FLAGS=--xla_dump_to)."""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture a device trace for the enclosed block (TensorBoard `logdir`)."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def profile_callable(fn: Callable, *args, logdir: str = "/tmp/tpu_profile",
                     warmup: int = 1, iters: int = 3, **kwargs):
    """Profile ``fn(*args, **kwargs)``: warm (compile), then trace ``iters`` runs.

    Returns (last_result, wall_seconds_per_iter). ≈ the reference's profile-largest-
    bucket flow (`utils/profiling.py:66-121`) without the NEFF bookkeeping."""
    for _ in range(warmup):
        result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    t0 = time.perf_counter()
    with trace(logdir):
        for _ in range(iters):
            result = fn(*args, **kwargs)
            jax.block_until_ready(result)
    return result, (time.perf_counter() - t0) / max(iters, 1)


def enable_hlo_dump(dump_dir: str) -> None:
    """Ask XLA to dump HLO for every subsequent compile (≈ `--hlo-debug` metadata,
    `inference_demo.py:383-388`). Must run before the first jit compilation."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_dump_to" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} --xla_dump_to={dump_dir}".strip()


def annotate(name: str):
    """Named trace span (shows up in the profiler timeline)."""
    return jax.profiler.TraceAnnotation(name)
