"""Benchmark harness: latency percentiles, TTFT, throughput, JSON report.

≈ reference `utils/benchmark.py` (`benchmark_sampling` :21-203, percentile report
:479-494, `benchmark_report.json` :199-201). Metrics keep the reference's definitions:
latency percentiles p50/p90/p95/p99/p100/avg over e2e generate calls; throughput =
(n_runs * output_tokens * batch) / total_time. Adds TTFT and decode-only tok/s, which
are the BASELINE.md headline metrics.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

BENCHMARK_REPORT_FILENAME = "benchmark_report.json"

# submodel names follow the reference's constants (`utils/benchmark.py:380-429`)
CONTEXT_ENCODING_MODEL = "context_encoding_model"
TOKEN_GENERATION_MODEL = "token_generation_model"
SPECULATION_MODEL = "speculation_model"
VISION_ENCODER_MODEL = "vision_encoder_model"

# Per-submodel latency registry (≈ the reference's forward pre/post hooks,
# `create_submodule_latency_collectors`/`register_latency_collectors`
# `utils/benchmark.py:380-414`). Functional JAX has no module hooks, so the
# runtimes call `record_submodel(...)` at their dispatch sites (prefill, decode
# chunk, speculative step, vision encode); recording is a no-op unless a
# `submodel_collection()` scope is active.
_ACTIVE_SUBMODELS: Optional[Dict[str, "LatencyCollector"]] = None


@contextlib.contextmanager
def submodel_collection():
    """Scope under which runtime dispatch sites record per-submodel latencies.
    Yields the {submodel_name: LatencyCollector} dict being filled."""
    global _ACTIVE_SUBMODELS
    prev, _ACTIVE_SUBMODELS = _ACTIVE_SUBMODELS, {}
    try:
        yield _ACTIVE_SUBMODELS
    finally:
        _ACTIVE_SUBMODELS = prev


def record_submodel(name: str, seconds: float) -> None:
    """Record one latency sample for a submodel; no-op outside a collection scope."""
    if _ACTIVE_SUBMODELS is None:
        return
    _ACTIVE_SUBMODELS.setdefault(name, LatencyCollector()).samples_s.append(seconds)


def generate_submodel_reports(
        collectors: Dict[str, "LatencyCollector"]) -> Dict[str, Dict[str, float]]:
    """Percentile report per submodel (≈ `generate_submodule_reports` :415-429)."""
    return {name: c.report() for name, c in collectors.items() if c.samples_s}


@dataclass
class BenchmarkReport:
    e2e_latency_ms: Dict[str, float]
    ttft_ms: Dict[str, float]
    decode_tok_s: float
    throughput_tok_s: float
    n_runs: int
    batch_size: int
    max_new_tokens: int
    extra: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {
            "e2e_model": self.e2e_latency_ms,
            "ttft_ms": self.ttft_ms,
            "decode_tokens_per_second": self.decode_tok_s,
            "throughput_tokens_per_second": self.throughput_tok_s,
            "n_runs": self.n_runs,
            "batch_size": self.batch_size,
            "max_new_tokens": self.max_new_tokens,
            **self.extra,
        }

    def save(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, BENCHMARK_REPORT_FILENAME)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path


def percentiles(values_s: List[float]) -> Dict[str, float]:
    """p50/p90/p95/p99/p100/avg in milliseconds (reference metric definitions).

    THE percentile definition for every serving surface: bench.py's phases,
    `utils/metrics.ServingTelemetry.snapshot()` (runner.stats()), and the
    submodel reports all route through here, so their keys cannot drift."""
    arr = np.asarray(values_s, dtype=np.float64) * 1e3
    return {
        "latency_ms_p50": float(np.percentile(arr, 50)),
        "latency_ms_p90": float(np.percentile(arr, 90)),
        "latency_ms_p95": float(np.percentile(arr, 95)),
        "latency_ms_p99": float(np.percentile(arr, 99)),
        "latency_ms_p100": float(np.percentile(arr, 100)),
        "latency_ms_avg": float(np.mean(arr)),
    }


def decode_tok_per_s(out, batch: int) -> float:
    """Decode tokens/s from a ``collect_latency`` generate output (shared by
    bench.py's phases — previously hand-rolled there)."""
    total_s = sum(t for t, _ in out.decode_latencies_s)
    total_toks = sum(n for _, n in out.decode_latencies_s) * batch
    return total_toks / total_s


def benchmark_sampling(
    app,
    input_ids: Optional[np.ndarray] = None,
    max_new_tokens: int = 64,
    n_runs: int = 5,
    warmup_runs: int = 1,
    report_dir: Optional[str] = None,
    submodel_breakdown: bool = True,
) -> BenchmarkReport:
    """Measure end-to-end generate latency/throughput (≈ `benchmark_sampling` :21).

    ``submodel_breakdown`` additionally reports per-submodel latency percentiles
    (context encoding / token generation chunks / speculation steps / vision encode)
    under ``extra["submodels"]`` (≈ reference `utils/benchmark.py:380-429`)."""
    cfg = app.tpu_config
    if input_ids is None:
        rng = np.random.default_rng(0)
        prompt_len = max(8, cfg.max_context_length // 2)
        input_ids = rng.integers(1, app.arch_args.vocab_size,
                                 size=(cfg.batch_size, prompt_len)).astype(np.int32)

    for _ in range(warmup_runs):
        app.generate(input_ids, max_new_tokens=max_new_tokens)

    e2e: List[float] = []
    ttft: List[float] = []
    decode_s = 0.0
    decode_tokens = 0
    generated_tokens = 0
    scope = submodel_collection() if submodel_breakdown else contextlib.nullcontext({})
    total_t0 = time.perf_counter()
    with scope as collectors:
        for _ in range(n_runs):
            t0 = time.perf_counter()
            out = app.generate(input_ids, max_new_tokens=max_new_tokens,
                               collect_latency=True)
            e2e.append(time.perf_counter() - t0)
            ttft.append(out.ttft_s)
            generated_tokens += out.tokens.size
            for s, toks in out.decode_latencies_s or []:
                decode_s += s
                decode_tokens += toks * input_ids.shape[0]
    total_time = time.perf_counter() - total_t0

    report = BenchmarkReport(
        e2e_latency_ms=percentiles(e2e),
        ttft_ms=percentiles(ttft),
        decode_tok_s=decode_tokens / decode_s if decode_s else 0.0,
        throughput_tok_s=generated_tokens / total_time,
        n_runs=n_runs,
        batch_size=int(input_ids.shape[0]),
        max_new_tokens=max_new_tokens,
    )
    if submodel_breakdown and collectors:
        report.extra["submodels"] = generate_submodel_reports(collectors)
    if report_dir:
        report.save(report_dir)
    return report


class LatencyCollector:
    """Context-manager timer collecting wall-clock samples
    (≈ reference `LatencyCollector` forward-hook timers, `utils/benchmark.py:432-477`;
    functional JAX has no module hooks, so collection wraps call sites instead)."""

    def __init__(self) -> None:
        self.samples_s: List[float] = []
        self._t0 = 0.0

    def __enter__(self) -> "LatencyCollector":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.samples_s.append(time.perf_counter() - self._t0)

    def report(self) -> Dict[str, float]:
        return percentiles(self.samples_s)
