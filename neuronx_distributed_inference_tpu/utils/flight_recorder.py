"""Flight recorder: a bounded ring of the last N serving step records plus a
one-call debug-bundle dump for faults.

The ring shares the step-record dicts ``ServingTelemetry.step_record``
appends (kind, host timestamps, duration, occupancy, KV state, in-flight
depth — and, once the runner drains the in-graph carry, the cumulative
device counters under ``"device"``), so it costs one deque append per
dispatch and is always warm when something goes wrong.

``dump_bundle`` writes a single self-contained JSON file: schema tag,
wall-clock stamp, package/jax versions, the serving config, a metrics
snapshot, the ring contents, and a pointer to any live XLA HLO dump
(``--xla_dump_to``) — everything a bug report needs to be triaged without
the box. ``load_bundle`` round-trips it (tests/test_flight_recorder_slo.py
pins dump → parse → matches live ``stats()``).

Fault hooks: ``install_signal_dump`` arms a SIGUSR1 (by default) handler
that dumps the bundle from a live serving process; the CLI's
``--debug-bundle`` flag additionally dumps on an unhandled serving-loop
exception (inference_demo.py).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import signal
import sys
import time
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("tpu-inference")

__all__ = ["FlightRecorder", "BUNDLE_SCHEMA", "load_bundle",
           "install_signal_dump"]

BUNDLE_SCHEMA = "tpu-inference-debug-bundle/1"


def _versions(mods: tuple = ("jax", "jaxlib", "numpy")) -> Dict[str, str]:
    """Best-effort module-version table (shared probe: the provenance
    fingerprint reuses it with its own module list — one place to fix)."""
    out = {"python": sys.version.split()[0]}
    for mod in mods:
        try:
            out[mod] = __import__(mod).__version__
        except Exception:
            out[mod] = "unavailable"
    return out


def _hlo_dump_dir() -> Optional[str]:
    """Pointer to a live XLA HLO dump if one is configured (the bundle
    records WHERE the HLO landed, never the multi-GB dump itself)."""
    m = re.search(r"--xla_dump_to=(\S+)", os.environ.get("XLA_FLAGS", ""))
    return m.group(1) if m else None


def _jsonable(obj):
    """Best-effort JSON coercion: numpy scalars/arrays, dataclass-ish
    configs, and anything else via repr — a debug bundle must never fail to
    serialize because one field was exotic."""
    import dataclasses

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return repr(obj)


class FlightRecorder:
    """Bounded ring of the last ``capacity`` step records."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.dropped = 0              # records evicted by the ring bound

    def record(self, rec: dict) -> None:
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append(rec)

    def records(self) -> List[dict]:
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._ring)

    # ------------------------------------------------------------------ bundle
    def dump_bundle(self, path: str, *, config=None, metrics=None,
                    stats=None, reason: str = "manual",
                    spans=None, extra: Optional[dict] = None) -> str:
        """Write the debug bundle to ``path`` and return it.

        ``config``: the serving TpuConfig (or any dataclass/dict);
        ``metrics``: a MetricsRegistry dump (``registry.to_dict()``);
        ``stats``: a live ``runner.stats()`` snapshot; ``reason``: what
        triggered the dump (``manual`` / ``signal`` / ``exception`` / ...);
        ``spans``: span trees of the requests in flight at dump time
        (``serving.tracing.inflight_span_trees`` — the post-mortem shows
        WHERE each live stream was, not just that streams existed).

        The bundle also carries the hardware/software provenance
        fingerprint (utils/provenance.py) — GUARDED like the span
        enrichment: a fingerprint failure records an error string, it
        never masks the fault being dumped. A live ``stats()`` snapshot
        passed via ``stats`` already embeds the last roofline join
        (``stats()["roofline"]``), so bundles are hardware-attributable
        end to end.
        """
        try:
            from . import provenance as _prov

            prov = _prov.fingerprint()
        except Exception as e:          # never mask the fault being dumped
            prov = {"error": f"{type(e).__name__}: {e}"}
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "created_unix": time.time(),
            "reason": reason,
            "provenance": prov,
            "versions": _versions(),
            "hlo_dump": _hlo_dump_dir(),
            "config": _jsonable(config),
            "metrics": _jsonable(metrics),
            "stats": _jsonable(stats),
            "ring": _jsonable(self.records()),
            "ring_dropped": self.dropped,
            "spans": _jsonable(spans),
            "extra": _jsonable(extra),
        }
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(bundle, fh, indent=1)
        os.replace(tmp, path)       # atomic: a fault mid-dump never truncates
        return path


def load_bundle(path: str) -> dict:
    """Parse a debug bundle; raises on schema mismatch (a bundle from a
    future incompatible layout must fail loudly, not half-parse)."""
    with open(path) as fh:
        bundle = json.load(fh)
    if bundle.get("schema") != BUNDLE_SCHEMA:
        raise ValueError(f"not a {BUNDLE_SCHEMA} bundle: "
                         f"{bundle.get('schema')!r}")
    return bundle


def install_signal_dump(dump: Callable[[str], str],
                        signum: int = signal.SIGUSR1):
    """Arm ``signum`` to dump a debug bundle from a live serving process.

    ``dump(reason)`` is the caller's closure (it knows the runner/paths);
    returns the previous handler so callers can restore it."""
    def _handler(sig, frame):
        del sig, frame
        try:
            logger.warning("debug bundle written to %s", dump("signal"))
        except Exception as e:                        # never kill the server
            logger.warning("debug-bundle dump failed: %s", e)

    return signal.signal(signum, _handler)
