"""Hardware/software provenance fingerprinting for perf artifacts.

The repo's perf trajectory mixes TPU-driver-captured rounds (r1-r5) with
CPU-container rounds (r6-r7), and until now only prose in the snapshot files
told them apart. This module makes the distinction STRUCTURAL:

- ``fingerprint()``: one process-cached dict — platform, device kind+count,
  the resolved roofline device spec (analysis/perf_model.py) and whether it
  is VERIFIED, jax/jaxlib/libtpu versions, git sha, and an anonymized host
  class — stamped into every bench snapshot, debug bundle
  (utils/flight_recorder.py) and, via ``stamp_registry``, a Prometheus
  ``build_info``-style metric.
- ``key``: the provenance GROUP a snapshot belongs to ("tpu-v5e",
  "cpu-container", ...). scripts/perf_trajectory.py groups the committed
  snapshots by it, so cross-hardware numbers are never compared as one
  series.
- the HARDWARE-CLAIM refusal: keys that normalize a measurement against a
  hardware peak (``hbm_bw_utilization``, ``prefill_mfu_bf16``) may only be
  published under a verified spec. ``claim_key``/``apply_to_extra`` rename
  them ``*_unverified`` otherwise — the r5 honesty pattern (refuse the
  number's NAME, keep the measurement visible), made structural so a
  CPU-container run can never masquerade as the TPU trajectory again.
"""

from __future__ import annotations

import hashlib
import logging
import os
import socket
import subprocess
from typing import Dict, Optional

logger = logging.getLogger("tpu-inference")

__all__ = ["SCHEMA", "HARDWARE_CLAIM_KEYS", "fingerprint", "claim_key",
           "apply_to_extra", "flat_labels", "stamp_registry"]

SCHEMA = "tpu-inference-provenance/1"

# bench ``extra`` keys that CLAIM a hardware-normalized efficiency: each
# divides a measurement by a device peak, so under an unverified spec the
# denominator is a guess and the NAME must say so. Absolute tok/s keys stay
# un-renamed (they are honest measurements of this box); the refusal for
# cross-hardware headline comparisons is the ``tpu_baseline_comparable``
# flag apply_to_extra stamps (top-level ``vs_baseline`` is driver-parsed
# schema and cannot be renamed without breaking the harness contract).
HARDWARE_CLAIM_KEYS = ("hbm_bw_utilization", "prefill_mfu_bf16")

_FP: Optional[dict] = None


def _git_sha() -> Optional[str]:
    try:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"], cwd=repo,
            capture_output=True, text=True, timeout=10, check=False)
        sha = out.stdout.strip()
        return sha or None
    except Exception:
        return None


def _versions() -> Dict[str, str]:
    from .flight_recorder import _versions as _probe

    out = _probe(("jax", "jaxlib"))
    try:
        import importlib.metadata as _md

        out["libtpu"] = _md.version("libtpu")
    except Exception:
        out["libtpu"] = "absent"
    return out


def fingerprint(refresh: bool = False) -> dict:
    """The process's hardware/software fingerprint (cached after the first
    call — the git subprocess and device probe run once, never per scrape
    or per step). ``refresh=True`` re-probes (tests)."""
    global _FP
    if _FP is not None and not refresh:
        return dict(_FP)
    import jax

    from ..analysis import perf_model

    dev = jax.devices()[0]
    spec = perf_model.resolve_device_spec(dev)
    platform = getattr(dev, "platform", "unknown") or "unknown"
    _FP = {
        "schema": SCHEMA,
        # the provenance GROUP: hardware class for verified specs, the
        # "<platform>-container" catch-all otherwise — what the trajectory
        # checker separates series by
        "key": spec.name if spec.verified else f"{platform}-container",
        "verified": spec.verified,
        "capture": "local",
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or "",
        "device_count": jax.device_count(),
        "device_spec": spec.name,
        "versions": _versions(),
        "git_sha": _git_sha(),
        # anonymized host CLASS (a short hostname digest): distinguishes
        # boxes within one provenance group (r06's container was ~6x slower
        # than r07's) without recording the hostname itself
        "host_class": hashlib.sha256(
            socket.gethostname().encode()).hexdigest()[:8],
    }
    return dict(_FP)


def claim_key(name: str, fp: Optional[dict] = None) -> str:
    """The name a hardware-claim bench key must publish under: unchanged on
    a verified spec, ``<name>_unverified`` otherwise. Write sites use this
    so the refusal is structural — the verified name cannot be produced on
    unverified hardware at all."""
    fp = fp if fp is not None else fingerprint()
    return name if fp.get("verified") else f"{name}_unverified"


def apply_to_extra(extra: dict, fp: Optional[dict] = None) -> dict:
    """Safety net over a bench ``extra`` dict (idempotent; mutates AND
    returns it): stamp the provenance block, rename any hardware-claim key
    that slipped in under its verified name, and on unverified specs flag
    that absolute tok/s and ``vs_baseline`` are not comparable to the
    TPU-measured baseline trajectory."""
    fp = fp if fp is not None else fingerprint()
    extra["provenance"] = fp
    if fp.get("verified"):
        return extra
    for name in HARDWARE_CLAIM_KEYS:
        if name in extra:
            extra[f"{name}_unverified"] = extra.pop(name)
    extra["tpu_baseline_comparable"] = False
    return extra


def flat_labels(fp: Optional[dict] = None) -> Dict[str, str]:
    """Flat string labels for the ``build_info``-style metric (nested
    version dicts flattened; every value stringified for exposition)."""
    fp = fp if fp is not None else fingerprint()
    v = fp.get("versions", {})
    return {
        "key": str(fp.get("key")),
        "verified": "1" if fp.get("verified") else "0",
        "platform": str(fp.get("platform")),
        "device_kind": str(fp.get("device_kind")),
        "device_count": str(fp.get("device_count")),
        "jax": str(v.get("jax")),
        "git_sha": str(fp.get("git_sha")),
        "host_class": str(fp.get("host_class")),
    }


def stamp_registry(registry, fp: Optional[dict] = None):
    """Register the ``serving_build_info`` info-style gauge (value pinned to
    1; the payload is the labels — the Prometheus ``build_info``
    convention) on ``registry``. Safe to call repeatedly (get-or-create)."""
    return registry.info(
        "serving_build_info",
        labels=flat_labels(fp),
        help="hardware/software provenance of this serving process "
             "(info-style: value pinned to 1, payload in the labels)")
