"""Runtime environment management (≈ reference `utils/runtime_env.py:6-38` +
`utils/compile_env.py:6-41`, which set `NEURON_RT_*` / compiler env for long-context
and MXFP4 runs). TPU equivalents are XLA flags and JAX config knobs."""

from __future__ import annotations

import os
from typing import Dict, Optional

# flags appended for >=32k-context runs (≈ the reference's long-context runtime env:
# scratchpad page size + DMA options, `models/config.py:577-587`)
LONG_CONTEXT_THRESHOLD = 32 * 1024


def _append_xla_flags(flags: str) -> None:
    cur = os.environ.get("XLA_FLAGS", "")
    present = {f.split("=")[0] for f in cur.split()}
    for f in flags.split():
        if f.split("=")[0] not in present:
            cur = f"{cur} {f}".strip()
    os.environ["XLA_FLAGS"] = cur


def set_runtime_env(seq_len: int, compilation_cache_dir: Optional[str] = None,
                    host_device_count: Optional[int] = None) -> Dict[str, str]:
    """Configure process env/JAX config for a serving run. Call BEFORE the first
    device query / jit. Returns the knobs applied (for logging)."""
    applied = {}
    if compilation_cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", compilation_cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        applied["jax_compilation_cache_dir"] = compilation_cache_dir
    if host_device_count:
        _append_xla_flags(
            f"--xla_force_host_platform_device_count={host_device_count}")
        applied["host_device_count"] = str(host_device_count)
    if seq_len >= LONG_CONTEXT_THRESHOLD:
        # long-context: lean on latency-hiding scheduling and async collectives so
        # CP/SP collectives overlap compute (≈ --enable-ccop-compute-overlap)
        _append_xla_flags("--xla_tpu_enable_async_collective_fusion=true")
        applied["long_context"] = "true"
    return applied
