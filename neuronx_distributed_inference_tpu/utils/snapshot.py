"""Input snapshot capture for bug repro (≈ reference `utils/snapshot.py:18-451`).

Env-driven like the reference's ``NXD_INFERENCE_CAPTURE_*`` hooks
(`models/application_base.py:421-476`):

- ``TPUINF_CAPTURE_DIR``       — enable capture, write .npz files here
- ``TPUINF_CAPTURE_AT``        — comma-separated request indices ("0,5"); empty = all
- ``TPUINF_CAPTURE_WEIGHTS=1`` — also snapshot the (host copies of) weights once

The application calls ``maybe_capture("prefill", {...})`` at its step boundaries; the
saved artifacts replay a failing input against a fresh build without the serving stack.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

_counter = {"n": -1}


def _config():
    d = os.environ.get("TPUINF_CAPTURE_DIR")
    if not d:
        return None
    at = os.environ.get("TPUINF_CAPTURE_AT", "")
    indices = ({int(x) for x in at.split(",") if x.strip()} if at.strip() else None)
    return d, indices


def new_request() -> int:
    """Advance the request counter (call once per generate())."""
    _counter["n"] += 1
    return _counter["n"]


def maybe_capture(tag: str, arrays: Dict[str, Any],
                  request_index: Optional[int] = None) -> Optional[str]:
    """Save arrays to <dir>/request{i}_{tag}.npz when capture is enabled for this
    request. Returns the path written, or None."""
    cfg = _config()
    if cfg is None:
        return None
    directory, indices = cfg
    idx = _counter["n"] if request_index is None else request_index
    if indices is not None and idx not in indices:
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"request{idx}_{tag}.npz")
    np.savez(path, **{k: np.asarray(v) for k, v in arrays.items() if v is not None})
    return path


def maybe_capture_weights(params) -> Optional[str]:
    """One-time weight snapshot when TPUINF_CAPTURE_WEIGHTS=1."""
    cfg = _config()
    if cfg is None or os.environ.get("TPUINF_CAPTURE_WEIGHTS") != "1":
        return None
    directory, _ = cfg
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "weights.npz")
    if os.path.exists(path):
        return path
    import jax

    flat = {}

    def visit(p, x):
        flat["/".join(str(getattr(k, "key", k)) for k in p)] = np.asarray(x)

    jax.tree_util.tree_map_with_path(visit, params)
    np.savez(path, **flat)
    return path
