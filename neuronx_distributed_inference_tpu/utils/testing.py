"""Public module/kernel test harness.

≈ reference `utils/testing.py` (`build_module`/`build_function` :123-267 compile any
nn.Module/fn at arbitrary tp_degree; `validate_accuracy` :67-120 compares against a
CPU callable) — the standard pattern for kernel-vs-native parity tests. TPU version:

- ``build_function(fn, tp_degree=...)`` jits ``fn`` over a fresh dp/cp/tp/ep mesh and
  (optionally) shards its inputs by logical axes — one call replaces the reference's
  ModelBuilder trace + NEFF load.
- ``validate_accuracy(device_fn, golden_fn, args)`` runs both and asserts closeness
  with per-dtype default tolerances (≈ the reference's tol maps).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np

from ..parallel import mesh as mesh_lib
from ..parallel.sharding import named_sharding

# default absolute tolerances per compute dtype (≈ reference per-dtype tol maps,
# `test_llama3_1_8b_4layer_dtype.py:31-54`)
DEFAULT_ATOL = {"float32": 2e-5, "bfloat16": 2e-2, "float16": 2e-3}


def build_mesh(tp_degree: int = 1, dp_degree: int = 1, cp_degree: int = 1,
               ep_degree: int = 1):
    return mesh_lib.build_mesh(tp_degree=tp_degree, dp_degree=dp_degree,
                               cp_degree=cp_degree, ep_degree=ep_degree)


def build_function(fn: Callable, tp_degree: int = 1, dp_degree: int = 1,
                   ep_degree: int = 1,
                   in_logical: Optional[Sequence] = None,
                   static_argnames: Sequence[str] = ()) -> Callable:
    """Jit ``fn`` for execution over a (tp, dp, ep) mesh.

    ``in_logical``: optional per-positional-argument logical-axis tuples (None =
    replicated); inputs are device_put with the derived shardings before the call, so
    GSPMD partitions the function the way serving would.
    """
    mesh = build_mesh(tp_degree=tp_degree, dp_degree=dp_degree, ep_degree=ep_degree)
    jitted = jax.jit(fn, static_argnames=tuple(static_argnames))

    def run(*args, **kwargs):
        placed = []
        for i, a in enumerate(args):
            logical = in_logical[i] if in_logical and i < len(in_logical) else None
            if logical is not None:
                a = jax.device_put(a, named_sharding(mesh, logical))
            placed.append(a)
        with mesh:
            return jitted(*placed, **kwargs)

    run.mesh = mesh
    return run


def validate_accuracy(device_fn: Callable, golden_fn: Callable, args: Sequence[Any],
                      kwargs: Optional[Dict[str, Any]] = None,
                      atol: Optional[float] = None, rtol: float = 1e-3,
                      dtype: str = "float32") -> None:
    """Run ``device_fn`` and ``golden_fn`` on the same inputs and assert the outputs
    match leaf-by-leaf (≈ reference `validate_accuracy`, `utils/testing.py:67-120`)."""
    kwargs = kwargs or {}
    got = jax.tree.leaves(device_fn(*args, **kwargs))
    want = jax.tree.leaves(golden_fn(*args, **kwargs))
    if len(got) != len(want):
        raise AssertionError(f"output arity mismatch: {len(got)} vs {len(want)}")
    tol = atol if atol is not None else DEFAULT_ATOL.get(dtype, 2e-5)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(np.asarray(g, dtype=np.float32),
                                   np.asarray(w, dtype=np.float32),
                                   atol=tol, rtol=rtol,
                                   err_msg=f"output leaf {i} diverged")
