"""Public module/kernel test harness.

≈ reference `utils/testing.py` (`build_module`/`build_function` :123-267 compile any
nn.Module/fn at arbitrary tp_degree; `validate_accuracy` :67-120 compares against a
CPU callable) — the standard pattern for kernel-vs-native parity tests. TPU version:

- ``build_function(fn, tp_degree=...)`` jits ``fn`` over a fresh dp/cp/tp/ep mesh and
  (optionally) shards its inputs by logical axes — one call replaces the reference's
  ModelBuilder trace + NEFF load.
- ``validate_accuracy(device_fn, golden_fn, args)`` runs both and asserts closeness
  with per-dtype default tolerances (≈ the reference's tol maps).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np

from ..parallel import mesh as mesh_lib
from ..parallel.sharding import named_sharding

# default absolute tolerances per compute dtype (≈ reference per-dtype tol maps,
# `test_llama3_1_8b_4layer_dtype.py:31-54`)
DEFAULT_ATOL = {"float32": 2e-5, "bfloat16": 2e-2, "float16": 2e-3}


def build_mesh(tp_degree: int = 1, dp_degree: int = 1, cp_degree: int = 1,
               ep_degree: int = 1):
    return mesh_lib.build_mesh(tp_degree=tp_degree, dp_degree=dp_degree,
                               cp_degree=cp_degree, ep_degree=ep_degree)


def build_function(fn: Callable, tp_degree: int = 1, dp_degree: int = 1,
                   ep_degree: int = 1,
                   in_logical: Optional[Sequence] = None,
                   static_argnames: Sequence[str] = ()) -> Callable:
    """Jit ``fn`` for execution over a (tp, dp, ep) mesh.

    ``in_logical``: optional per-positional-argument logical-axis tuples (None =
    replicated); inputs are device_put with the derived shardings before the call, so
    GSPMD partitions the function the way serving would.
    """
    mesh = build_mesh(tp_degree=tp_degree, dp_degree=dp_degree, ep_degree=ep_degree)
    jitted = jax.jit(fn, static_argnames=tuple(static_argnames))

    def run(*args, **kwargs):
        placed = []
        for i, a in enumerate(args):
            logical = in_logical[i] if in_logical and i < len(in_logical) else None
            if logical is not None:
                a = jax.device_put(a, named_sharding(mesh, logical))
            placed.append(a)
        with mesh:
            return jitted(*placed, **kwargs)

    run.mesh = mesh
    return run


def validate_accuracy(device_fn: Callable, golden_fn: Callable, args: Sequence[Any],
                      kwargs: Optional[Dict[str, Any]] = None,
                      atol: Optional[float] = None, rtol: float = 1e-3,
                      dtype: str = "float32") -> None:
    """Run ``device_fn`` and ``golden_fn`` on the same inputs and assert the outputs
    match leaf-by-leaf (≈ reference `validate_accuracy`, `utils/testing.py:67-120`)."""
    kwargs = kwargs or {}
    got = jax.tree.leaves(device_fn(*args, **kwargs))
    want = jax.tree.leaves(golden_fn(*args, **kwargs))
    if len(got) != len(want):
        raise AssertionError(f"output arity mismatch: {len(got)} vs {len(want)}")
    tol = atol if atol is not None else DEFAULT_ATOL.get(dtype, 2e-5)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(np.asarray(g, dtype=np.float32),
                                   np.asarray(w, dtype=np.float32),
                                   atol=tol, rtol=rtol,
                                   err_msg=f"output leaf {i} diverged")


def extract_layer_params(params, layer_idx: int):
    """Slice ONE decoder layer's params out of a loaded app's stacked tree.

    ≈ reference module-from-model test templates
    (`module_test/module_from_model_template/`): families stack per-layer
    weights as (L, ...) arrays under ``params["layers"]``; this returns the
    {name: (…)} dict for ``layer_idx``, usable directly with the shared
    ``models.base._decoder_layer`` (or any family-level layer fn) for
    module-level validation against a reference implementation.
    """
    return {k: v[layer_idx] for k, v in params["layers"].items()}


def run_decoder_layer(app, layer_idx: int, hidden, position_ids=None):
    """Run one decoder layer of a loaded causal-LM app on ``hidden`` (B, S, H),
    prefill-style (fresh KV, full causal mask), returning its output hidden.

    The single-module analog of a full forward: extract the layer, build the
    rope tables and mask exactly as the traced prefill does, call the shared
    `_decoder_layer`. Use with `validate_accuracy` against the corresponding
    HF layer for module-level parity hunting.
    """
    import jax.numpy as jnp
    import numpy as np

    from ..models import base as model_base
    from ..ops import rope as rope_ops

    args = app.arch_args
    h = jnp.asarray(hidden)
    b, s, _ = h.shape
    if position_ids is None:
        position_ids = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    else:
        position_ids = jnp.asarray(position_ids)
    cos, sin = rope_ops.compute_cos_sin(app.params["rope_inv_freq"],
                                        position_ids,
                                        args.rope_attention_scaling)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask = jnp.logical_and(mask, model_base.causal_mask(s, s)[None, None])
    lp = extract_layer_params(app.params, layer_idx)
    k_cache = jnp.zeros((b, args.num_kv_heads, s, args.head_dim), h.dtype)
    v_cache = jnp.zeros_like(k_cache)
    out, _, _ = model_base._decoder_layer(
        lp, args, h, cos, sin, mask, k_cache, v_cache,
        positions=None, decode_bucket=None, mesh=None, rules=None)
    return np.asarray(out)
