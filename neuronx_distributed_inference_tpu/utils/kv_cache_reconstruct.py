"""Reconstruct logical KV caches for debugging.

≈ reference `utils/kv_cache_reconstruct_utils.py:57-218`, which de-shards per-rank
device caches back into the logical (B, H, S, D). On TPU the cache is a GSPMD-sharded
`jax.Array` whose logical view is already global — `np.asarray` performs the gather —
so reconstruction reduces to slicing + dtype restoration, plus paged-cache block
unpacking."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def reconstruct_dense(cache: Dict, seq_len: Optional[int] = None,
                      batch: Optional[int] = None) -> List[Dict[str, np.ndarray]]:
    """{"k","v" (L, B, H, S, D)} -> per-layer {"k","v" (B, H, S', D)} float32."""
    out = []
    k_all, v_all = np.asarray(cache["k"]), np.asarray(cache["v"])
    s = seq_len if seq_len is not None else k_all.shape[3]
    b = batch if batch is not None else k_all.shape[1]
    for layer in range(k_all.shape[0]):
        out.append({
            "k": k_all[layer, :b, :, :s].astype(np.float32),
            "v": v_all[layer, :b, :, :s].astype(np.float32),
        })
    return out


def reconstruct_paged(cache: Dict, block_table: np.ndarray,
                      seq_lens: np.ndarray) -> List[Dict[str, np.ndarray]]:
    """Paged cache (L, num_blocks, block_size, H, D) + per-seq block tables ->
    per-layer contiguous {"k","v" (B, H, S_max, D)}."""
    k_all, v_all = np.asarray(cache["k"]), np.asarray(cache["v"])
    L, _, block_size, H, D = k_all.shape
    bt = np.asarray(block_table)
    b = bt.shape[0]
    s_max = int(np.max(seq_lens))
    out = []
    for layer in range(L):
        k = np.zeros((b, H, s_max, D), dtype=np.float32)
        v = np.zeros((b, H, s_max, D), dtype=np.float32)
        for row in range(b):
            n = int(seq_lens[row])
            gathered_k = k_all[layer, bt[row]].reshape(-1, H, D)[:n]
            gathered_v = v_all[layer, bt[row]].reshape(-1, H, D)[:n]
            k[row, :, :n] = gathered_k.transpose(1, 0, 2)
            v[row, :, :n] = gathered_v.transpose(1, 0, 2)
        out.append({"k": k, "v": v})
    return out


def cache_summary(cache: Dict) -> Dict[str, str]:
    """Shapes/dtypes/shardings of every cache entry (quick debug print)."""
    import jax

    out = {}
    for name, arr in cache.items():
        sh = getattr(arr, "sharding", None)
        out[name] = f"{jax.typeof(arr) if hasattr(jax, 'typeof') else arr.shape} " \
                    f"sharding={sh}"
    return out
