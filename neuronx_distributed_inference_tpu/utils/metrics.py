"""Serving observability: metrics registry + per-request lifecycle telemetry.

Dependency-free (numpy only) counterpart of a Prometheus client plus a
Chrome-trace step timeline, sized for the continuous-batching serving loop:

- ``MetricsRegistry``: counters, gauges, and FIXED-BUCKET histograms with a
  near-zero-cost disabled path (disabled registries hand out shared null
  instruments whose ``inc``/``set``/``observe`` are one-attribute no-ops),
  exported as Prometheus text exposition or a plain dict.
- ``ServingTelemetry``: the serving loop's event spine. Per-request lifecycle
  events (arrival → placement → prefill chunks → first token → decode commits
  → preemption/resume → prefix hits → finish) aggregate into TTFT / TPOT /
  queue-wait percentiles; every dispatch records a STEP event (kind,
  occupancy, tokens committed, iterations, prefill-budget use, KV blocks,
  spec acceptance) exportable as Chrome/Perfetto trace-event JSON; events can
  be spooled to JSONL as they happen. ``annotate(kind)`` wraps host dispatch
  spans in ``jax.profiler`` trace annotations so the host timeline aligns
  with device traces (utils/profiling.py).

The registry is ALWAYS live inside a runner (counter updates are rare host
events — preemptions, chunk boundaries — and cost an int add); the
``enabled`` flag gates the per-step / per-token event recording, which is the
only part with hot-path frequency. tests/test_perf_regression.py pins the
disabled path's per-step overhead.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

# latency-shaped default buckets (seconds): 1 ms .. 60 s, ~log-spaced
DEFAULT_TIME_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                        0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


# ------------------------------------------------------------------ instruments
class Counter:
    """Monotonic counter. ``value`` is a plain int/float; ``inc`` is the only
    mutator (back-compat properties may also assign ``value`` directly)."""

    __slots__ = ("name", "help", "labels", "value")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help, self.labels = name, help, labels
        self.value = 0

    def inc(self, n=1) -> None:
        self.value += n


class Gauge:
    """Point-in-time value. ``updated`` distinguishes "never set" from 0.0
    (back-compat: the runner's ``_round_trip_s`` is None until measured)."""

    __slots__ = ("name", "help", "labels", "value", "updated")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help, self.labels = name, help, labels
        self.value = 0.0
        self.updated = False

    def set(self, v) -> None:
        self.value = float(v)
        self.updated = True


class Histogram:
    """Fixed-bucket histogram: ``buckets`` are ascending upper bounds with an
    implicit +Inf overflow bucket appended; ``counts`` is a LIVE np.int64
    array of len(buckets)+1 (integer-valued histograms like spec acceptance
    expose ``counts[:K]`` as the back-compat ``acceptance_counts`` view).

    ``observe(v, exemplar={"trace_id": ...})`` additionally remembers the
    LAST exemplar per bucket (labels, value, unix ts) — the OpenMetrics
    exemplar wiring that lets a scraped TTFT/TPOT bucket name the request
    trace that landed in it (serving/tracing.py). Exemplar storage is lazy:
    a histogram that never sees one keeps ``exemplars`` None and the observe
    hot path pays a single ``is not None`` test."""

    __slots__ = ("name", "help", "labels", "buckets", "counts", "sum", "_bk",
                 "exemplars")

    def __init__(self, name: str, buckets: Sequence[float], help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError("histogram buckets must be non-empty ascending "
                             "upper bounds")
        self.name, self.help, self.labels = name, help, labels
        self.buckets = tuple(float(b) for b in buckets)
        self._bk = np.asarray(self.buckets, dtype=np.float64)
        self.counts = np.zeros(len(self.buckets) + 1, dtype=np.int64)
        self.sum = 0.0
        self.exemplars: Optional[Dict[int, tuple]] = None

    def observe(self, v, exemplar: Optional[Dict[str, str]] = None) -> None:
        # side="left": an observation equal to a bound lands IN that bucket
        # (le semantics), so integer buckets [1..K] map value k -> counts[k-1]
        idx = int(np.searchsorted(self._bk, v, side="left"))
        self.counts[idx] += 1
        self.sum += v
        if exemplar is not None:
            if self.exemplars is None:
                self.exemplars = {}
            self.exemplars[idx] = (dict(exemplar), float(v), time.time())

    @property
    def count(self) -> int:
        return int(self.counts.sum())


class _Null:
    """Shared no-op instrument for disabled registries: every mutator returns
    immediately; reads are inert defaults."""

    name = help = ""
    labels = None
    value = 0
    updated = False
    sum = 0.0
    count = 0
    buckets = ()

    def inc(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v, exemplar=None):
        pass

    @property
    def counts(self):
        return np.zeros(1, dtype=np.int64)


_NULL = _Null()


def acceptance_mean(counts: np.ndarray) -> float:
    """Mean committed tokens/row/iteration from an acceptance histogram whose
    bucket i counts iterations that committed i+1 tokens (the shared helper:
    runner.stats(), bench.py's spec phases, and eagle engines all read the
    histogram through this one definition)."""
    counts = np.asarray(counts)
    total = int(counts.sum())
    if total == 0:
        return 0.0
    return float((counts * (np.arange(counts.size) + 1)).sum() / total)


# ------------------------------------------------------------------ registry
def _key(name: str, labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Name-keyed get-or-create store of instruments.

    ``enabled=False`` hands out the shared null instrument — the zero-cost
    path for callers that want instrumented code with no accounting at all
    (the serving runner keeps its registry enabled and gates only the
    event-recording side; see module docstring).

    ``default_labels``: labels merged into EVERY instrument this registry
    creates (per-call labels win on key collision). The scale-out engine
    split (serving/engine.py) threads ``{"replica": "<id>"}`` here so every
    counter a replica's runner registers carries the replica label without
    any per-call-site threading — N replicas' registries concatenate into
    one exposition where series stay distinguishable."""

    def __init__(self, enabled: bool = True,
                 default_labels: Optional[Dict[str, str]] = None):
        self.enabled = enabled
        self.default_labels = (dict(default_labels) if default_labels
                               else None)
        self._metrics: Dict[str, object] = {}

    def _merge_labels(self, labels: Optional[Dict[str, str]]
                      ) -> Optional[Dict[str, str]]:
        if not self.default_labels:
            return labels
        if not labels:
            return dict(self.default_labels)
        return {**self.default_labels, **labels}

    def _get(self, cls, name, help, labels, **kw):
        if not self.enabled:
            return _NULL
        labels = self._merge_labels(labels)
        key = _key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            m = cls(name, help=help, labels=labels, **kw)
            self._metrics[key] = m
        elif type(m) is not cls:
            raise ValueError(f"metric {key!r} already registered as "
                             f"{type(m).__name__}, requested {cls.__name__}")
        return m

    def get(self, name: str, labels: Optional[Dict[str, str]] = None):
        """Peek an instrument WITHOUT registering it (None when absent) —
        read-side consumers (the SLO monitor) must not create series. The
        default labels apply here too, so a reader that names only the
        series-specific labels finds the replica-labelled instrument."""
        return self._metrics.get(_key(name, self._merge_labels(labels)))

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                  help: str = "",
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def info(self, name: str, labels: Optional[Dict[str, str]] = None,
             help: str = "") -> Gauge:
        """Info-style gauge (the Prometheus ``build_info`` convention): the
        VALUE is pinned to 1 and the payload lives in the labels — joins
        and dashboards multiply by it to attribute series to a build/
        hardware fingerprint (utils/provenance.stamp_registry). Get-or-
        create like every instrument; re-calling re-pins 1 (a reset()
        between bench windows zeroes it like any gauge, so stampers re-call
        after reset)."""
        g = self._get(Gauge, name, help, labels)
        g.set(1)
        return g

    def reset(self) -> None:
        """Zero every instrument IN PLACE (cached instrument references stay
        valid — bench measurement windows reset between phases)."""
        for m in self._metrics.values():
            if isinstance(m, Counter):
                m.value = 0
            elif isinstance(m, Gauge):
                m.value, m.updated = 0.0, False
            elif isinstance(m, Histogram):
                m.counts[:] = 0
                m.sum = 0.0
                m.exemplars = None

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for key, m in self._metrics.items():
            if isinstance(m, Histogram):
                out[key] = {"buckets": list(m.buckets),
                            "counts": m.counts.tolist(),
                            "sum": m.sum, "count": m.count}
            elif isinstance(m, Gauge):
                out[key] = m.value if m.updated else None
            else:
                out[key] = m.value
        return out

    def prometheus_text(self, exemplars: bool = False) -> str:
        """Prometheus text exposition (version 0.0.4): HELP/TYPE headers,
        cumulative ``le``-labelled histogram buckets ending at +Inf, _sum and
        _count series.

        ``exemplars=True`` appends OpenMetrics exemplar suffixes
        (``# {trace_id="..."} value unix_ts``) to histogram bucket lines that
        have one. GATED off by default: exemplar syntax is OpenMetrics, not
        Prometheus text 0.0.4, and a plain-Prometheus scraper must keep
        receiving valid exposition (tests/test_tracing.py pins both shapes)."""
        lines: List[str] = []
        seen_header = set()
        for m in self._metrics.values():
            kind = {Counter: "counter", Gauge: "gauge",
                    Histogram: "histogram"}[type(m)]
            if m.name not in seen_header:
                seen_header.add(m.name)
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {kind}")
            base = dict(m.labels) if m.labels else {}
            if isinstance(m, Histogram):
                cum = 0
                for i, (b, c) in enumerate(zip(m.buckets + (float("inf"),),
                                               m.counts)):
                    cum += int(c)
                    line = _series(f"{m.name}_bucket",
                                   {**base, "le": _le(b)}, cum)
                    if exemplars and m.exemplars and i in m.exemplars:
                        ex_labels, ex_val, ex_ts = m.exemplars[i]
                        inner = ",".join(f'{k}="{v}"'
                                         for k, v in ex_labels.items())
                        line += f" # {{{inner}}} {ex_val} {ex_ts:.3f}"
                    lines.append(line)
                lines.append(_series(f"{m.name}_sum", base, m.sum))
                lines.append(_series(f"{m.name}_count", base, m.count))
            elif isinstance(m, Gauge):
                lines.append(_series(m.name, base,
                                     m.value if m.updated else 0.0))
            else:
                lines.append(_series(m.name, base, m.value))
        return "\n".join(lines) + ("\n" if lines else "")


def _le(bound: float) -> str:
    if bound == float("inf"):
        return "+Inf"
    return repr(bound) if bound != int(bound) else str(int(bound))


def _series(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


# ------------------------------------------------------------------ telemetry
class ServingTelemetry:
    """Event spine of the continuous-batching serving loop.

    ``enabled=False`` (the runner default) turns every event/step recorder
    into an immediate return — the registry stays live for the always-on
    counters (preemptions, spec acceptance) but nothing per-step or
    per-token is recorded. All timestamps share ONE clock
    (``time.perf_counter``) so ``stats()`` percentiles and the JSONL event
    log are recomputable from each other."""

    def __init__(self, enabled: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 jsonl_path: Optional[str] = None,
                 max_records: Optional[int] = 200_000,
                 flight_records: int = 256):
        self.enabled = enabled
        self.registry = registry if registry is not None else MetricsRegistry()
        self.events: List[dict] = []        # lifecycle event log
        self.steps: List[dict] = []         # step timeline
        self.requests: Dict[int, dict] = {}
        # flight recorder: bounded ring of the last N step records, dumpable
        # as a debug bundle on fault/signal (utils/flight_recorder.py). The
        # ring shares the step-record dicts, so drained device counters
        # attached via note_device_counters() appear in the ring too.
        from .flight_recorder import FlightRecorder

        self.flight = FlightRecorder(flight_records) if flight_records else None
        # latest drained device-counter snapshot (the in-graph telemetry
        # carry, utils/device_telemetry.py) and the last profiled per-kind
        # device-time attribution (runner.attribute_device_time)
        self.device_counters: Optional[Dict[str, object]] = None
        self.timing: Optional[Dict[str, dict]] = None
        # last measured-vs-roofline-model join (analysis/perf_model.py),
        # attached by runner.attribute_device_time alongside ``timing`` —
        # never computed here (the model's AOT lowering must stay off every
        # telemetry path; a plain read is all snapshot() does)
        self.roofline: Optional[Dict[str, object]] = None
        # in-memory retention bound for long-lived serving: past
        # ``max_records`` entries per log the OLDEST quarter is dropped (and
        # counted — no silent truncation; the registry aggregates and the
        # JSONL spool keep the full history). None = unbounded.
        self.max_records = max_records
        self._t0 = time.perf_counter()      # trace epoch
        # per-instance trace-id salt: replicas minting their own ids (no
        # router upstream) must not collide when their event logs merge into
        # one fleet trace (serving/tracing.py)
        import uuid

        self._trace_salt = uuid.uuid4().hex[:8]
        self._trace_seq = 0
        self._jsonl = None
        if jsonl_path and enabled:
            self._jsonl = open(jsonl_path, "w")
            self._write_epoch_line()
        reg = self.registry
        self._c_steps: Dict[str, Counter] = {}   # per-kind cache (hot path)
        self._c_dropped = reg.counter(
            "serving_telemetry_dropped_records_total",
            "in-memory event/step/request records evicted past max_records")
        self._c_requests = reg.counter(
            "serving_requests_total", "requests submitted")
        self._c_finished = reg.counter(
            "serving_requests_finished_total", "requests finished")
        self._c_tokens = reg.counter(
            "serving_tokens_emitted_total", "tokens emitted to clients")
        self._c_prefill = reg.counter(
            "serving_prefill_tokens_total", "prompt tokens written")
        self._c_prefix = reg.counter(
            "serving_prefix_hit_tokens_total",
            "prompt tokens skipped via prefix-cache hits")
        self._h_ttft = reg.histogram(
            "serving_ttft_seconds", help="arrival to first emitted token")
        self._h_tpot = reg.histogram(
            "serving_tpot_seconds", DEFAULT_TIME_BUCKETS,
            help="per-output-token time after the first token")
        self._h_queue = reg.histogram(
            "serving_queue_wait_seconds", help="arrival to slot placement")
        self._g_kv_free = reg.gauge("serving_kv_blocks_free")
        self._g_kv_used = reg.gauge("serving_kv_blocks_used")
        self._g_queue = reg.gauge("serving_queue_depth")
        self._g_occupancy = reg.gauge("serving_batch_occupancy",
                                      "live decode rows in the last step")

    # ------------------------------------------------------------ event log
    @property
    def epoch(self) -> float:
        """The stream's clock origin as a ``time.perf_counter()`` value:
        every event/step ``ts`` is relative to this. Same-process sources
        (router + N replicas) normalize onto ONE shared epoch by adding it
        back — the clock model the fleet-merged trace export is built on."""
        return self._t0

    def _write_epoch_line(self) -> None:
        """Spool the clock origin so an OFFLINE reader (explain_request.py)
        can place this file's relative timestamps on the shared process
        clock. Re-written on reset(): everything before the newest epoch
        line belongs to a discarded measurement window."""
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(
                {"event": "telemetry_epoch", "epoch": self._t0,
                 "unix_ts": time.time()}) + "\n")

    def mint_trace_id(self) -> str:
        self._trace_seq += 1
        return f"t-{self._trace_salt}-{self._trace_seq:06x}"

    def trace_id_of(self, rid: int) -> Optional[str]:
        r = self.requests.get(rid)
        return r.get("trace_id") if r is not None else None

    def _trim(self, log: List) -> None:
        if self.max_records is not None and len(log) > self.max_records:
            n = self.max_records // 4
            del log[:n]
            self._c_dropped.inc(n)

    def _event(self, event: str, request_id: Optional[int] = None,
               _ts: Optional[float] = None, **fields):
        rec = {"ts": (_ts if _ts is not None else time.perf_counter())
               - self._t0, "event": event}
        if request_id is not None:
            rec["request_id"] = request_id
        rec.update(fields)
        self.events.append(rec)
        self._trim(self.events)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
        return rec

    def request_arrival(self, rid: int, prompt_len: int,
                        max_new_tokens: int,
                        ts: Optional[float] = None,
                        trace_id: Optional[str] = None,
                        sla_class: Optional[str] = None) -> None:
        """``ts``: optional ``time.perf_counter()`` timestamp of when the
        request ACTUALLY arrived upstream (defaults to now). Open-loop
        drivers backdate to the scheduled arrival so queue wait spent inside
        a blocking step() is not hidden by submit granularity.

        ``trace_id``: request-scoped trace context (serving/tracing.py) —
        the router mints one at frontend submit and threads it through
        placement so a request's events stay joinable across replicas; a
        standalone runner's telemetry mints its own. Minted only on the
        ENABLED path (the disabled path must stay allocation-free).

        ``sla_class``: the tenant tier (serving/sla.py). Stamped on the
        record (the SLO monitor's per-class targets and offender
        attribution key on it) and every TTFT/TPOT/queue-wait observation
        of a classed request ALSO lands in the ``sla_class``-labelled
        histogram series beside the fleet-wide one."""
        self._c_requests.inc()
        if not self.enabled:
            return
        if trace_id is None:
            trace_id = self.mint_trace_id()
        rec = self._event("arrival", rid, _ts=ts, prompt_len=prompt_len,
                          max_new_tokens=max_new_tokens, trace_id=trace_id,
                          **({"sla_class": sla_class} if sla_class else {}))
        self.requests[rid] = {
            "arrival_ts": rec["ts"], "placed_ts": None, "first_token_ts": None,
            "last_token_ts": None, "finish_ts": None, "prompt_len": prompt_len,
            "tokens": 0, "prefill_tokens": 0, "prefix_hit_tokens": 0,
            "preemptions": 0, "finish_reason": None, "tpot_observed": False,
            "trace_id": trace_id, "sla_class": sla_class,
        }

    def request_placed(self, rid: int, slot: int, resumed: bool = False) -> None:
        if not self.enabled:
            return
        rec = self._event("placed", rid, slot=slot, resumed=resumed)
        r = self.requests.get(rid)
        if r is not None and r["placed_ts"] is None:
            r["placed_ts"] = rec["ts"]
            self._h_queue.observe(rec["ts"] - r["arrival_ts"],
                                  exemplar=self._exemplar(r))
            self._class_observe(self._h_queue, r,
                                rec["ts"] - r["arrival_ts"])

    def request_prefix_hit(self, rid: int, tokens: int) -> None:
        self._c_prefix.inc(tokens)
        if not self.enabled:
            return
        self._event("prefix_hit", rid, tokens=tokens)
        r = self.requests.get(rid)
        if r is not None:
            r["prefix_hit_tokens"] += tokens

    def request_prefill_chunk(self, rid: int, tokens: int, pos: int) -> None:
        if not self.enabled:
            return
        self._c_prefill.inc(tokens)
        self._event("prefill_chunk", rid, tokens=tokens, pos=pos)
        r = self.requests.get(rid)
        if r is not None:
            r["prefill_tokens"] += tokens

    def request_preempted(self, rid: int,
                          blocks_held: Optional[int] = None) -> None:
        """``blocks_held``: KV blocks the request held AT the preemption
        point (the block ledger's holdings-at-handoff attribution) — rides
        the event stream so offline trace readers (explain_request.py) see
        the hand-off's memory footprint without the live ledger."""
        if not self.enabled:
            return
        self._event("preempted", rid,
                    **({} if blocks_held is None
                       else {"blocks_held": blocks_held}))
        r = self.requests.get(rid)
        if r is not None:
            r["preemptions"] += 1

    def request_finished(self, rid: int, reason: str, n_tokens: int) -> None:
        self._c_finished.inc()
        if not self.enabled:
            return
        rec = self._event("finish", rid, reason=reason, tokens=n_tokens)
        r = self.requests.get(rid)
        if r is None:
            return
        r["finish_ts"], r["finish_reason"] = rec["ts"], reason
        self._maybe_observe_tpot(r)
        if (self.max_records is not None
                and len(self.requests) > self.max_records):
            # evict oldest FINISHED records (dict preserves insertion order);
            # histograms already hold their latency samples
            drop = [k for k, v in self.requests.items()
                    if v["finish_ts"] is not None][: self.max_records // 4]
            for k in drop:
                del self.requests[k]
            self._c_dropped.inc(len(drop))

    @staticmethod
    def _exemplar(r: Optional[dict]) -> Optional[Dict[str, str]]:
        """Exemplar labels for a latency observation: the request's trace id
        (None when untraced — the observe then skips exemplar storage)."""
        tid = r.get("trace_id") if r is not None else None
        return {"trace_id": tid} if tid else None

    def _class_observe(self, base: Histogram, r: Optional[dict], v) -> None:
        """Mirror one latency observation into the request's ``sla_class``-
        labelled series beside the fleet-wide histogram (serving/sla.py) —
        a classless request (or a disabled-path call, which never reaches
        here) costs one dict read."""
        cls = r.get("sla_class") if r is not None else None
        if not cls:
            return
        self.registry.histogram(base.name, base.buckets, help=base.help,
                                labels={"sla_class": cls}).observe(v)

    def _maybe_observe_tpot(self, r: dict) -> None:
        """Observe TPOT once per finished request — from finish OR from the
        step-end note_emitted, whichever lands last (the runner finishes a
        request inside the step, BEFORE the step's emissions are folded in)."""
        if (r["tpot_observed"] or r["finish_ts"] is None
                or r["first_token_ts"] is None or r["tokens"] <= 1):
            return
        r["tpot_observed"] = True
        tpot = (r["last_token_ts"] - r["first_token_ts"]) / (r["tokens"] - 1)
        self._h_tpot.observe(tpot, exemplar=self._exemplar(r))
        self._class_observe(self._h_tpot, r, tpot)

    def note_emitted(self, emitted: Dict[int, List[int]]) -> None:
        """Fold one step's {request_id: new tokens} into the per-request
        records: first-token events (TTFT) and per-commit events (TPOT)."""
        if not self.enabled or not emitted:
            return
        for rid, toks in emitted.items():
            if not toks:
                continue
            n = len(toks)
            self._c_tokens.inc(n)
            r = self.requests.get(rid)
            if r is None:
                continue
            if r["first_token_ts"] is None:
                rec = self._event("first_token", rid)
                r["first_token_ts"] = rec["ts"]
                self._h_ttft.observe(rec["ts"] - r["arrival_ts"],
                                     exemplar=self._exemplar(r))
                self._class_observe(self._h_ttft, r,
                                    rec["ts"] - r["arrival_ts"])
                ts = rec["ts"]
                self._event("commit", rid, tokens=n)
            else:
                ts = self._event("commit", rid, tokens=n)["ts"]
            r["tokens"] += n
            r["last_token_ts"] = ts
            self._maybe_observe_tpot(r)

    # ------------------------------------------------------------ step timeline
    def step_start(self) -> Optional[float]:
        """Hot-path entry: None (one attribute test) when disabled."""
        if not self.enabled:
            return None
        return time.perf_counter()

    def step_record(self, t0: Optional[float], kind: str, *, iterations: int = 0,
                    tokens: int = 0, occupancy: int = 0, slots: int = 0,
                    prefill_tokens: int = 0, prefill_budget: int = 0,
                    kv_free: Optional[int] = None, kv_total: Optional[int] = None,
                    accept_mean: Optional[float] = None,
                    request_id: Optional[int] = None,
                    in_flight: Optional[int] = None,
                    ici_bytes: Optional[int] = None,
                    extra: Optional[Dict[str, object]] = None) -> None:
        """Record one dispatch of the serving loop (kinds: ``decode``,
        ``spec_chunk``, ``mixed``, ``insert_window``, ``insert``,
        ``megastep``). Durations are host spans over dispatch + host commit;
        device overlap shows up through the paired ``annotate()`` spans in a
        jax.profiler trace. ``extra`` merges caller-specific fields into the
        record (megastep exit reason, scheduler fall-through reason) without
        widening this signature per kind."""
        if t0 is None or not self.enabled:
            return
        now = time.perf_counter()
        rec = {"ts": t0 - self._t0, "dur_s": now - t0, "kind": kind,
               "iterations": iterations, "tokens": tokens,
               "occupancy": occupancy, "slots": slots,
               "prefill_tokens": prefill_tokens,
               "prefill_budget": prefill_budget}
        if extra:
            rec.update(extra)
        if kv_total is not None:
            rec["kv_blocks_free"] = kv_free
            rec["kv_blocks_total"] = kv_total
            self._g_kv_free.set(kv_free)
            self._g_kv_used.set(kv_total - kv_free)
        if accept_mean is not None:
            rec["accept_mean"] = round(accept_mean, 4)
        if request_id is not None:
            rec["request_id"] = request_id
        if in_flight is not None:
            # dispatch-ahead pipeline occupancy at record time (the step
            # timeline's view of the depth-N pipeline; the registry gauges
            # serving_dispatch_depth / serving_inflight_chunks carry the
            # scrape-time values)
            rec["in_flight"] = in_flight
        if ici_bytes is not None:
            # per-dispatch inter-chip traffic (tp > 1 meshes only; the
            # runner's shape-derived estimate, parallel/overlap.py —
            # multichip runs become visible in the step timeline exports)
            rec["ici_bytes"] = ici_bytes
        c = self._c_steps.get(kind)
        if c is None:
            c = self.registry.counter("serving_steps_total",
                                      "dispatches by step kind",
                                      labels={"kind": kind})
            self._c_steps[kind] = c
        c.inc()
        self._g_occupancy.set(occupancy)
        self.steps.append(rec)
        self._trim(self.steps)
        if self.flight is not None:
            self.flight.record(rec)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps({"event": "step", **rec}) + "\n")

    def set_queue_depth(self, n: int) -> None:
        if self.enabled:
            self._g_queue.set(n)

    def note_device_counters(self, counters: Dict[str, object]) -> None:
        """Fold a drained device-counter snapshot (the in-graph telemetry
        carry) into the telemetry: becomes the latest ``device`` view in
        snapshot()/stats(), and is attached to the newest step record so the
        flight-recorder ring carries it (same dict object — the ring shares
        step records)."""
        if not self.enabled:
            return
        self.device_counters = counters
        if self.steps:
            self.steps[-1]["device"] = counters
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(
                {"event": "device_counters", **counters}) + "\n")

    def set_device_timing(self, timing: Dict[str, dict]) -> None:
        """Record a profiled per-kind device-time attribution (the runner's
        attribute_device_time result) for snapshot()["timing"]."""
        self.timing = timing

    def set_roofline(self, roofline: Optional[Dict[str, object]]) -> None:
        """Record the measured-vs-roofline-model join for
        snapshot()["roofline"] (runner.attribute_device_time attaches it
        next to the timing table it was joined against)."""
        self.roofline = roofline

    def annotate(self, kind: str):
        """jax.profiler host span for a dispatch (aligns the step timeline
        with device traces); a shared null context when disabled."""
        if not self.enabled:
            return contextlib.nullcontext()
        from . import profiling

        return profiling.annotate(f"serving_step:{kind}")

    # ------------------------------------------------------------ export
    def snapshot(self) -> Dict[str, object]:
        """Aggregate view: TTFT/TPOT/queue-wait percentiles from the RAW
        per-request records (the same samples the event log carries, so the
        two are mutually recomputable), per-kind step counts, and the full
        registry dump."""
        from .benchmark import percentiles

        ttft, queue_wait, tpot = [], [], []
        # per-SLA-class sample splits (serving/sla.py): populated only when
        # classed requests exist, so classless snapshots keep their shape
        by_class: Dict[str, Dict[str, list]] = {}
        for r in self.requests.values():
            cls = r.get("sla_class")
            c = (by_class.setdefault(
                cls, {"ttft": [], "tpot": [], "queue_wait": [], "tokens": []})
                if cls else None)
            if r["first_token_ts"] is not None:
                ttft.append(r["first_token_ts"] - r["arrival_ts"])
                if c is not None:
                    c["ttft"].append(ttft[-1])
            if r["placed_ts"] is not None:
                queue_wait.append(r["placed_ts"] - r["arrival_ts"])
                if c is not None:
                    c["queue_wait"].append(queue_wait[-1])
            if (r["first_token_ts"] is not None and r["tokens"] > 1
                    and r["last_token_ts"] is not None):
                tpot.append((r["last_token_ts"] - r["first_token_ts"])
                            / (r["tokens"] - 1))
                if c is not None:
                    c["tpot"].append(tpot[-1])
            if c is not None:
                c["tokens"].append(r["tokens"])
        steps: Dict[str, int] = {}
        tokens_by_kind: Dict[str, int] = {}
        for s in self.steps:
            steps[s["kind"]] = steps.get(s["kind"], 0) + 1
            tokens_by_kind[s["kind"]] = (tokens_by_kind.get(s["kind"], 0)
                                         + s["tokens"])
        out: Dict[str, object] = {
            "requests_submitted": self._c_requests.value,
            "requests_finished": self._c_finished.value,
            "tokens_emitted": self._c_tokens.value,
            "prefill_tokens": self._c_prefill.value,
            "prefix_hit_tokens": self._c_prefix.value,
            "steps": steps,
            "tokens_by_step_kind": tokens_by_kind,
            "ttft_ms": percentiles(ttft) if ttft else None,
            "tpot_ms": percentiles(tpot) if tpot else None,
            "queue_wait_ms": percentiles(queue_wait) if queue_wait else None,
            "counters": self.registry.to_dict(),
            # latest drained in-graph counter block (lags by <= async_depth
            # chunks in dispatch-ahead steady state; exact at pipeline flush)
            "device": self.device_counters,
            # per-kind device-time attribution of the last profiled window
            "timing": self.timing,
            # measured-vs-roofline-model join of the last profiled window
            # (analysis/perf_model.py; None until an attribution ran)
            "roofline": self.roofline,
        }
        if by_class:
            out["by_class"] = {
                cls: {
                    "requests": len(c["tokens"]),
                    "tokens": int(sum(c["tokens"])),
                    "ttft_ms": percentiles(c["ttft"]) if c["ttft"] else None,
                    "tpot_ms": percentiles(c["tpot"]) if c["tpot"] else None,
                    "queue_wait_ms": (percentiles(c["queue_wait"])
                                      if c["queue_wait"] else None),
                }
                for cls, c in sorted(by_class.items())}
        return out

    def chrome_trace(self) -> Dict[str, object]:
        """Chrome/Perfetto trace-event JSON: step dispatches as complete
        ("X") events on tid 0 carrying kind/occupancy/KV-utilization args,
        request lifecycle as instant ("i") events on tid 1."""
        evs: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "cb-serving"}},
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "steps"}},
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": 1,
             "args": {"name": "requests"}},
        ]
        for s in self.steps:
            args = {k: v for k, v in s.items() if k not in ("ts", "dur_s")}
            if s.get("kv_blocks_total"):
                args["kv_utilization"] = round(
                    1.0 - s["kv_blocks_free"] / s["kv_blocks_total"], 4)
            evs.append({"name": f"step:{s['kind']}", "ph": "X", "cat": "step",
                        "ts": s["ts"] * 1e6, "dur": s["dur_s"] * 1e6,
                        "pid": 0, "tid": 0, "args": args})
        for e in self.events:
            args = {k: v for k, v in e.items() if k not in ("ts", "event")}
            evs.append({"name": e["event"], "ph": "i", "s": "t",
                        "cat": "request", "ts": e["ts"] * 1e6,
                        "pid": 0, "tid": 1, "args": args})
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def prometheus_text(self, exemplars: bool = False) -> str:
        return self.registry.prometheus_text(exemplars=exemplars)

    def reset(self) -> None:
        """Clear events/steps/request records and zero the registry in place
        (bench measurement windows; cached instrument references stay valid)."""
        self.events.clear()
        self.steps.clear()
        self.requests.clear()
        self.registry.reset()
        self.device_counters = None
        self.timing = None
        self.roofline = None
        if self.flight is not None:
            self.flight.clear()
        self._t0 = time.perf_counter()
        # offline readers drop everything before the newest epoch line (the
        # discarded window's events reference a dead clock origin)
        self._write_epoch_line()

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
