"""Accuracy harness: token matching and logit matching against a CPU reference.

≈ reference `utils/accuracy.py` (`check_accuracy` :240 token matching,
`check_accuracy_logits` :474-697 logit matching with per-position tolerance maps and
divergence-index reporting). The reference callable is anything producing HF-style
outputs (typically a `transformers` model on CPU); ours is a TpuModelForCausalLM.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("tpu-inference")


@dataclass
class LogitMatchReport:
    passed: bool
    divergence_index: int              # first generation step whose argmax disagrees
    max_abs_error: float
    top1_match_rate: float
    per_step_max_err: List[float] = field(default_factory=list)


def check_token_accuracy(
    actual_tokens: np.ndarray,     # (B, T)
    expected_tokens: np.ndarray,   # (B, T)
    minimum_match_ratio: float = 1.0,
) -> bool:
    """Token-level match (≈ `check_accuracy` :240). Compares up to the first EOS/pad
    divergence and reports the match ratio per sequence."""
    actual = np.asarray(actual_tokens)
    expected = np.asarray(expected_tokens)
    t = min(actual.shape[1], expected.shape[1])
    ok = True
    for b in range(actual.shape[0]):
        matches = actual[b, :t] == expected[b, :t]
        ratio = float(matches.mean())
        if ratio < minimum_match_ratio:
            first_bad = int(np.argmin(matches))
            logger.warning(
                "seq %d: token match %.3f < %.3f (first divergence at step %d: "
                "%d != %d)", b, ratio, minimum_match_ratio, first_bad,
                actual[b, first_bad], expected[b, first_bad])
            ok = False
    return ok


def check_logit_accuracy(
    actual_logits: List[np.ndarray],    # per-step (B, V)
    expected_logits: List[np.ndarray],  # per-step (B, V)
    divergence_difference_tol: float = 0.001,
    tol_map: Optional[Dict[int, Tuple[float, float]]] = None,
) -> LogitMatchReport:
    """Logit matching with divergence-index semantics (≈ `check_accuracy_logits`).

    Steps are compared in order; the comparison for step i uses (rtol, atol) from the
    ``tol_map`` entry with the largest key <= i (reference's per-position tol maps,
    e.g. ``{0: (1e-5, 0.01), 50: (1e-5, 0.04)}``), defaulting to
    (1e-5, divergence_difference_tol).
    """
    tol_map = dict(sorted((tol_map or {}).items()))
    per_step_err: List[float] = []
    divergence_index = -1
    top1_hits = 0
    top1_total = 0
    passed = True

    for i, (got, want) in enumerate(zip(actual_logits, expected_logits)):
        got = np.asarray(got, dtype=np.float32)
        want = np.asarray(want, dtype=np.float32)
        rtol, atol = 1e-5, divergence_difference_tol
        for k, (r, a) in tol_map.items():
            if i >= k:
                rtol, atol = r, a
        err = float(np.max(np.abs(got - want)))
        per_step_err.append(err)
        top1 = np.argmax(got, axis=-1) == np.argmax(want, axis=-1)
        top1_hits += int(top1.sum())
        top1_total += top1.size
        if not top1.all() and divergence_index < 0:
            divergence_index = i
        if not np.allclose(got, want, rtol=rtol, atol=atol):
            passed = False
            logger.warning("logit mismatch at step %d: max|err|=%.5f (atol=%.5f)",
                           i, err, atol)

    return LogitMatchReport(
        passed=passed,
        divergence_index=divergence_index,
        max_abs_error=max(per_step_err) if per_step_err else 0.0,
        top1_match_rate=top1_hits / max(top1_total, 1),
        per_step_max_err=per_step_err,
    )


def get_hf_expected_outputs(hf_model, input_ids: np.ndarray, max_new_tokens: int,
                            attention_mask: Optional[np.ndarray] = None):
    """Greedy HF-CPU golden run returning (tokens (B,T), per-step logits list).

    ≈ the reference generating goldens via HF generate with output_scores. Each row is
    generated *unpadded* (HF's generate reads next-token logits from the last position,
    which under right padding would be a pad token for shorter rows), then reassembled
    into per-step (B, V) logits.
    """
    import torch

    input_ids = np.asarray(input_ids)
    b, s = input_ids.shape
    if attention_mask is None:
        lengths = np.full((b,), s, dtype=np.int64)
    else:
        lengths = np.asarray(attention_mask).sum(axis=1).astype(np.int64)

    # disable EOS stopping so goldens cover all max_new_tokens steps; the TPU side is
    # compared with eos disabled too (symmetric; EOS semantics are tested separately)
    saved_eos = hf_model.generation_config.eos_token_id
    hf_model.generation_config.eos_token_id = None
    try:
        rows_tokens = []
        rows_scores = []
        for i in range(b):
            row = input_ids[i, : lengths[i]][None, :]
            with torch.no_grad():
                out = hf_model.generate(
                    torch.tensor(row), max_new_tokens=max_new_tokens,
                    do_sample=False, pad_token_id=0, output_scores=True,
                    return_dict_in_generate=True)
            rows_tokens.append(out.sequences[0, lengths[i]:].numpy())
            rows_scores.append([sc[0].numpy() for sc in out.scores])
    finally:
        hf_model.generation_config.eos_token_id = saved_eos

    tokens = np.stack(rows_tokens)
    logits = [np.stack([rows_scores[i][t] for i in range(b)])
              for t in range(max_new_tokens)]
    return tokens, logits


def check_accuracy_vs_hf(app, hf_model, input_ids: np.ndarray, max_new_tokens: int,
                         attention_mask: Optional[np.ndarray] = None,
                         divergence_difference_tol: float = 0.001,
                         tol_map=None) -> LogitMatchReport:
    """One-call harness: run both sides greedy, token-match and logit-match."""
    expected_tokens, expected_logits = get_hf_expected_outputs(
        hf_model, input_ids, max_new_tokens, attention_mask)
    out = app.generate(np.asarray(input_ids), attention_mask=attention_mask,
                       max_new_tokens=max_new_tokens, return_logits=True)
    token_ok = check_token_accuracy(out.tokens, expected_tokens)
    report = check_logit_accuracy(out.logits, expected_logits,
                                  divergence_difference_tol, tol_map)
    report.passed = report.passed and token_ok
    return report


# ---------------------------------------------------------------------------
# Draft-logit matching (speculative decoding)
# ---------------------------------------------------------------------------


@dataclass
class DraftLogitReport:
    passed: bool
    checked_loops: int
    # (loop, draft_iter) of the first tolerance failure; None when passed
    first_failure: Optional[Tuple[int, int]]
    max_topk_err: float


def save_draft_goldens(directory: str, draft_logits_loops: List[np.ndarray]) -> None:
    """Save per-loop draft logits as ``draft_logits_{n}.npy`` (≈ the reference's
    ``draft_logits_{n}.pt`` golden dirs, `utils/accuracy.py:1233-1240`)."""
    import os

    os.makedirs(directory, exist_ok=True)
    for i, arr in enumerate(draft_logits_loops):
        np.save(os.path.join(directory, f"draft_logits_{i}.npy"), np.asarray(arr))


def load_draft_goldens(directory: str) -> List[np.ndarray]:
    """Load goldens saved by :func:`save_draft_goldens`, sorted by loop number."""
    import os
    import re

    nums = sorted(int(m.group(1)) for f in os.listdir(directory)
                  if (m := re.match(r"draft_logits_(\d+)\.npy$", f)))
    return [np.load(os.path.join(directory, f"draft_logits_{n}.npy")) for n in nums]


def check_accuracy_draft_logits(
    actual_loops: List[np.ndarray],     # per spec step: (B, K-1, V) draft logits
    expected_loops: List[np.ndarray],   # goldens, same shape
    num_loops_to_check: int = 6,
    top_k: int = 2,
    rtol: float = 1e-5,
    atol: float = 0.02,
) -> DraftLogitReport:
    """Per-draft-loop logit matching (≈ `check_accuracy_draft_logit` /
    `check_logits_per_draft_loop`, reference `utils/accuracy.py:1214-1268`).

    For each draft loop, each draft iteration's actual logits are compared at the
    golden's top-``top_k`` token positions (allclose within rtol/atol). A tolerance
    failure fails the check; a top-1 *token* divergence (argmax mismatch without a
    tolerance failure) only stops further validation within that loop — later
    iterations were conditioned on a different token, exactly the reference's
    early-stop semantics."""
    passed = True
    first_failure = None
    max_err = 0.0
    n = min(num_loops_to_check, len(actual_loops), len(expected_loops))
    if n == 0:
        # a silent pass over zero comparisons would defeat the check (empty or
        # wrong golden dir, or a capture that produced no loops)
        raise ValueError(
            f"no draft loops to compare (actual={len(actual_loops)}, "
            f"expected={len(expected_loops)})")
    for loop in range(n):
        got = np.asarray(actual_loops[loop], dtype=np.float32)    # (B, K-1, V)
        want = np.asarray(expected_loops[loop], dtype=np.float32)
        if got.ndim == 2:                   # unbatched (K-1, V) goldens
            got, want = got[None], want[None]
        iters = min(got.shape[1], want.shape[1])
        for i in range(iters):
            idx = np.argsort(want[:, i], axis=-1)[:, -top_k:]      # (B, top_k)
            got_k = np.take_along_axis(got[:, i], idx, axis=-1)
            want_k = np.take_along_axis(want[:, i], idx, axis=-1)
            err = float(np.max(np.abs(got_k - want_k)))
            max_err = max(max_err, err)
            if not np.allclose(got_k, want_k, rtol=rtol, atol=atol):
                logger.warning(
                    "draft logit mismatch at loop %d iter %d: max|err|=%.5f "
                    "(atol=%.5f)", loop, i, err, atol)
                if passed:
                    first_failure = (loop, i)
                passed = False
                break
            if (np.argmax(got[:, i], axis=-1)
                    != np.argmax(want[:, i], axis=-1)).any():
                logger.info(
                    "draft tokens diverge at loop %d iter %d; validated up to "
                    "here in this loop", loop, i)
                break
        if not passed:
            break
    return DraftLogitReport(passed=passed, checked_loops=n,
                            first_failure=first_failure, max_topk_err=max_err)


def check_draft_accuracy_vs_reference(
    spec_model, golden_source, input_ids: np.ndarray, max_new_tokens: int = 32,
    num_loops_to_check: int = 6, top_k: int = 2, atol: float = 0.02,
) -> DraftLogitReport:
    """One-call draft-logit flow (≈ `run_accuracy_draft_logit_test_flow` :1214):
    run the fused speculative model with draft-logit capture and compare against
    ``golden_source`` — a golden directory (str) or a list of per-loop arrays."""
    out = spec_model.generate(np.asarray(input_ids),
                              max_new_tokens=max_new_tokens,
                              capture_draft_logits=True)
    expected = (load_draft_goldens(golden_source)
                if isinstance(golden_source, str) else golden_source)
    return check_accuracy_draft_logits(out.draft_logits, expected,
                                       num_loops_to_check=num_loops_to_check,
                                       top_k=top_k, atol=atol)


# ---------------------------------------------------------------------------
# Chunked-prefill generation loop (paged KV accuracy path)
# ---------------------------------------------------------------------------


def generate_with_chunked_prefill(app, input_ids: np.ndarray,
                                  max_new_tokens: int,
                                  chunk_size: Optional[int] = None):
    """Generate through the chunked-prefill paged-KV path, returning per-step
    logits for accuracy comparison (≈ reference `generate_with_chunked_prefill`,
    `utils/accuracy.py:940-1030`).

    The prompt (all rows the same length, like the reference's
    ``[max_num_seqs, input_len]`` contract) is prefilled in lockstep chunks: each
    iteration feeds ``chunk_size`` tokens per row as a wide paged decode call whose
    queries see all prior chunks' KV through an identity block table. Decode then
    runs greedy one token at a time with logits captured.

    Returns ``(tokens (B, max_new_tokens), logits)`` where ``logits`` is a
    per-step list of (B, V) arrays — feed to :func:`check_logit_accuracy`.
    """
    import jax
    import jax.numpy as jnp

    from ..modules.block_kvcache import make_slot_mapping

    cfg = app.tpu_config
    if not cfg.paged_attention_enabled:
        raise ValueError("generate_with_chunked_prefill requires "
                         "paged_attention_enabled")
    input_ids = np.asarray(input_ids).astype(np.int32)
    b, s = input_ids.shape
    if s + max_new_tokens > cfg.seq_len:
        # out-of-range positions would map to slot -1 (dropped KV writes) and
        # silently corrupt later steps' attention instead of erroring
        raise ValueError(f"prompt ({s}) + max_new_tokens ({max_new_tokens}) "
                         f"exceeds seq_len {cfg.seq_len}")
    bs = cfg.pa_block_size
    nb_per_seq = -(-cfg.seq_len // bs)
    if b * nb_per_seq > cfg.pa_num_blocks:
        raise ValueError(f"need {b * nb_per_seq} blocks for {b} rows of "
                         f"seq_len {cfg.seq_len}, have {cfg.pa_num_blocks}")
    chunk = int(chunk_size or cfg.max_context_length)
    block_table = np.arange(b * nb_per_seq, dtype=np.int32).reshape(b, nb_per_seq)
    cache = app.make_paged_cache(cfg.pa_num_blocks, bs)

    args, mesh, rules = app.arch_args, app.mesh, app.sharding_rules
    decode_core = app.decode_fn()
    precision = "highest" if cfg.dtype == "float32" else "default"

    @jax.jit
    def _prefill_chunk(params, ids, pos, cache, table, slots):
        with jax.default_matmul_precision(precision):
            logits, cache = decode_core(params, args, ids, pos, cache, None,
                                        mesh=mesh, rules=rules,
                                        block_table=table, slot_mapping=slots)
        return logits, cache

    @jax.jit
    def _decode_one(params, tok, pos, cache, table, slots):
        with jax.default_matmul_precision(precision):
            logits, cache = decode_core(params, args, tok[:, None], pos, cache,
                                        None, mesh=mesh, rules=rules,
                                        block_table=table, slot_mapping=slots)
        return logits[:, -1], cache

    table_dev = jnp.asarray(block_table)
    last_logits = None
    for start in range(0, s, chunk):
        end = min(start + chunk, s)
        w = end - start
        ids = np.zeros((b, chunk), dtype=np.int32)
        ids[:, :w] = input_ids[:, start:end]
        valid = np.zeros((b, chunk), dtype=bool)
        valid[:, :w] = True
        pos = np.full((b,), start, dtype=np.int32)
        slots = make_slot_mapping(block_table, pos, chunk, bs, valid=valid)
        logits, cache = _prefill_chunk(app.params, jnp.asarray(ids),
                                       jnp.asarray(pos), cache, table_dev,
                                       jnp.asarray(slots))
        last_logits = np.asarray(logits[:, w - 1])       # (B, V)

    all_logits = [last_logits]
    tok = np.argmax(last_logits, axis=-1).astype(np.int32)
    tokens = [tok]
    positions = np.full((b,), s, dtype=np.int32)
    for _ in range(max_new_tokens - 1):
        slots = make_slot_mapping(block_table, positions, 1, bs)
        step_logits, cache = _decode_one(app.params, jnp.asarray(tok),
                                         jnp.asarray(positions), cache,
                                         table_dev, jnp.asarray(slots))
        step_logits = np.asarray(step_logits)
        all_logits.append(step_logits)
        tok = np.argmax(step_logits, axis=-1).astype(np.int32)
        tokens.append(tok)
        positions = positions + 1
    return np.stack(tokens, axis=1), all_logits
