"""Accuracy harness: token matching and logit matching against a CPU reference.

≈ reference `utils/accuracy.py` (`check_accuracy` :240 token matching,
`check_accuracy_logits` :474-697 logit matching with per-position tolerance maps and
divergence-index reporting). The reference callable is anything producing HF-style
outputs (typically a `transformers` model on CPU); ours is a TpuModelForCausalLM.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("tpu-inference")


@dataclass
class LogitMatchReport:
    passed: bool
    divergence_index: int              # first generation step whose argmax disagrees
    max_abs_error: float
    top1_match_rate: float
    per_step_max_err: List[float] = field(default_factory=list)


def check_token_accuracy(
    actual_tokens: np.ndarray,     # (B, T)
    expected_tokens: np.ndarray,   # (B, T)
    minimum_match_ratio: float = 1.0,
) -> bool:
    """Token-level match (≈ `check_accuracy` :240). Compares up to the first EOS/pad
    divergence and reports the match ratio per sequence."""
    actual = np.asarray(actual_tokens)
    expected = np.asarray(expected_tokens)
    t = min(actual.shape[1], expected.shape[1])
    ok = True
    for b in range(actual.shape[0]):
        matches = actual[b, :t] == expected[b, :t]
        ratio = float(matches.mean())
        if ratio < minimum_match_ratio:
            first_bad = int(np.argmin(matches))
            logger.warning(
                "seq %d: token match %.3f < %.3f (first divergence at step %d: "
                "%d != %d)", b, ratio, minimum_match_ratio, first_bad,
                actual[b, first_bad], expected[b, first_bad])
            ok = False
    return ok


def check_logit_accuracy(
    actual_logits: List[np.ndarray],    # per-step (B, V)
    expected_logits: List[np.ndarray],  # per-step (B, V)
    divergence_difference_tol: float = 0.001,
    tol_map: Optional[Dict[int, Tuple[float, float]]] = None,
) -> LogitMatchReport:
    """Logit matching with divergence-index semantics (≈ `check_accuracy_logits`).

    Steps are compared in order; the comparison for step i uses (rtol, atol) from the
    ``tol_map`` entry with the largest key <= i (reference's per-position tol maps,
    e.g. ``{0: (1e-5, 0.01), 50: (1e-5, 0.04)}``), defaulting to
    (1e-5, divergence_difference_tol).
    """
    tol_map = dict(sorted((tol_map or {}).items()))
    per_step_err: List[float] = []
    divergence_index = -1
    top1_hits = 0
    top1_total = 0
    passed = True

    for i, (got, want) in enumerate(zip(actual_logits, expected_logits)):
        got = np.asarray(got, dtype=np.float32)
        want = np.asarray(want, dtype=np.float32)
        rtol, atol = 1e-5, divergence_difference_tol
        for k, (r, a) in tol_map.items():
            if i >= k:
                rtol, atol = r, a
        err = float(np.max(np.abs(got - want)))
        per_step_err.append(err)
        top1 = np.argmax(got, axis=-1) == np.argmax(want, axis=-1)
        top1_hits += int(top1.sum())
        top1_total += top1.size
        if not top1.all() and divergence_index < 0:
            divergence_index = i
        if not np.allclose(got, want, rtol=rtol, atol=atol):
            passed = False
            logger.warning("logit mismatch at step %d: max|err|=%.5f (atol=%.5f)",
                           i, err, atol)

    return LogitMatchReport(
        passed=passed,
        divergence_index=divergence_index,
        max_abs_error=max(per_step_err) if per_step_err else 0.0,
        top1_match_rate=top1_hits / max(top1_total, 1),
        per_step_max_err=per_step_err,
    )


def get_hf_expected_outputs(hf_model, input_ids: np.ndarray, max_new_tokens: int,
                            attention_mask: Optional[np.ndarray] = None):
    """Greedy HF-CPU golden run returning (tokens (B,T), per-step logits list).

    ≈ the reference generating goldens via HF generate with output_scores. Each row is
    generated *unpadded* (HF's generate reads next-token logits from the last position,
    which under right padding would be a pad token for shorter rows), then reassembled
    into per-step (B, V) logits.
    """
    import torch

    input_ids = np.asarray(input_ids)
    b, s = input_ids.shape
    if attention_mask is None:
        lengths = np.full((b,), s, dtype=np.int64)
    else:
        lengths = np.asarray(attention_mask).sum(axis=1).astype(np.int64)

    # disable EOS stopping so goldens cover all max_new_tokens steps; the TPU side is
    # compared with eos disabled too (symmetric; EOS semantics are tested separately)
    saved_eos = hf_model.generation_config.eos_token_id
    hf_model.generation_config.eos_token_id = None
    try:
        rows_tokens = []
        rows_scores = []
        for i in range(b):
            row = input_ids[i, : lengths[i]][None, :]
            with torch.no_grad():
                out = hf_model.generate(
                    torch.tensor(row), max_new_tokens=max_new_tokens,
                    do_sample=False, pad_token_id=0, output_scores=True,
                    return_dict_in_generate=True)
            rows_tokens.append(out.sequences[0, lengths[i]:].numpy())
            rows_scores.append([sc[0].numpy() for sc in out.scores])
    finally:
        hf_model.generation_config.eos_token_id = saved_eos

    tokens = np.stack(rows_tokens)
    logits = [np.stack([rows_scores[i][t] for i in range(b)])
              for t in range(max_new_tokens)]
    return tokens, logits


def check_accuracy_vs_hf(app, hf_model, input_ids: np.ndarray, max_new_tokens: int,
                         attention_mask: Optional[np.ndarray] = None,
                         divergence_difference_tol: float = 0.001,
                         tol_map=None) -> LogitMatchReport:
    """One-call harness: run both sides greedy, token-match and logit-match."""
    expected_tokens, expected_logits = get_hf_expected_outputs(
        hf_model, input_ids, max_new_tokens, attention_mask)
    out = app.generate(np.asarray(input_ids), attention_mask=attention_mask,
                       max_new_tokens=max_new_tokens, return_logits=True)
    token_ok = check_token_accuracy(out.tokens, expected_tokens)
    report = check_logit_accuracy(out.logits, expected_logits,
                                  divergence_difference_tol, tol_map)
    report.passed = report.passed and token_ok
    return report
