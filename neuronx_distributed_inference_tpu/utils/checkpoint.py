"""Checkpoint I/O: HF checkpoint ingestion and per-rank-free sharded loading.

≈ reference `modules/checkpoint.py` (`load_state_dict` :24, `create_n_layer_checkpoint`
:202) and the weight-sharding half of `models/application_base.py:240-265`. Differences
by design: TPU weights are not pre-sharded to per-rank files — we load the full
state dict host-side (or memory-map safetensors) and `jax.device_put` with
`NamedSharding`, letting the runtime slice each shard; multi-host sharded loading can
use `jax.make_array_from_callback` later without changing this API.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np

SAFETENSORS_INDEX = "model.safetensors.index.json"
SAFETENSORS_SINGLE = "model.safetensors"
PT_BIN_INDEX = "pytorch_model.bin.index.json"
PT_BIN_SINGLE = "pytorch_model.bin"


def _from_torch(t) -> np.ndarray:
    import torch

    if t.dtype == torch.bfloat16:
        # numpy has no bfloat16; round-trip through ml_dtypes
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def load_state_dict(model_dir: str, keys: Optional[Iterable[str]] = None
                    ) -> Dict[str, np.ndarray]:
    """Load a HF checkpoint directory into {name: np.ndarray}.

    Handles sharded/unsharded safetensors and pytorch .bin, like the reference
    `modules/checkpoint.py:24-120`. ``keys`` optionally restricts which tensors load
    (used for per-modality / per-layer loading).
    """
    if os.path.exists(os.path.join(model_dir, SAFETENSORS_INDEX)):
        with open(os.path.join(model_dir, SAFETENSORS_INDEX)) as f:
            index = json.load(f)["weight_map"]
        out: Dict[str, np.ndarray] = {}
        by_file: Dict[str, list] = {}
        for name, fname in index.items():
            if keys is not None and name not in keys:
                continue
            by_file.setdefault(fname, []).append(name)
        for fname, names in by_file.items():
            out.update(_load_safetensors_file(os.path.join(model_dir, fname), names))
        return out
    if os.path.exists(os.path.join(model_dir, SAFETENSORS_SINGLE)):
        return _load_safetensors_file(
            os.path.join(model_dir, SAFETENSORS_SINGLE),
            list(keys) if keys is not None else None)
    if os.path.exists(os.path.join(model_dir, PT_BIN_INDEX)):
        import torch

        with open(os.path.join(model_dir, PT_BIN_INDEX)) as f:
            index = json.load(f)["weight_map"]
        out = {}
        for fname in sorted(set(index.values())):
            sd = torch.load(os.path.join(model_dir, fname), map_location="cpu",
                            weights_only=True)
            for k, v in sd.items():
                if keys is None or k in keys:
                    out[k] = _from_torch(v)
        return out
    if os.path.exists(os.path.join(model_dir, PT_BIN_SINGLE)):
        import torch

        sd = torch.load(os.path.join(model_dir, PT_BIN_SINGLE), map_location="cpu",
                        weights_only=True)
        return {k: _from_torch(v) for k, v in sd.items()
                if keys is None or k in keys}
    raise FileNotFoundError(f"no checkpoint found under {model_dir}")


def _load_safetensors_file(path: str, names: Optional[list]) -> Dict[str, np.ndarray]:
    from safetensors import safe_open

    out: Dict[str, np.ndarray] = {}
    with safe_open(path, framework="np") as f:
        for name in (names if names is not None else f.keys()):
            out[name] = f.get_tensor(name)
    return out


def checkpoint_tensor_names(model_dir: str) -> list:
    """List tensor names without loading data."""
    if os.path.exists(os.path.join(model_dir, SAFETENSORS_INDEX)):
        with open(os.path.join(model_dir, SAFETENSORS_INDEX)) as f:
            return sorted(json.load(f)["weight_map"].keys())
    if os.path.exists(os.path.join(model_dir, SAFETENSORS_SINGLE)):
        from safetensors import safe_open

        with safe_open(os.path.join(model_dir, SAFETENSORS_SINGLE), framework="np") as f:
            return sorted(f.keys())
    return sorted(load_state_dict(model_dir).keys())


def save_state_dict(state_dict: Dict[str, np.ndarray], model_dir: str,
                    filename: str = SAFETENSORS_SINGLE) -> str:
    """Save {name: array} as a single safetensors file (≈ `modules/checkpoint.py`
    save path; pruning of None values included)."""
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    path = os.path.join(model_dir, filename)
    clean = {}
    for k, v in state_dict.items():
        if v is None:
            continue
        arr = np.asarray(v)
        if arr.dtype.kind not in "fiub" and arr.dtype.name != "bfloat16":
            raise ValueError(f"cannot serialize {k} with dtype {arr.dtype}")
        clean[k] = np.ascontiguousarray(arr)
    save_file(clean, path)
    return path


def create_n_layer_checkpoint(hf_config, n_layers: int, out_dir: str, seed: int = 0,
                              config_overrides: Optional[Dict[str, Any]] = None) -> str:
    """Create a truncated random-weight HF checkpoint for testing.

    ≈ reference `modules/checkpoint.py:202` + `test/integration/utils/test_utils.py:16-49`:
    instantiate the architecture from its config with ``num_hidden_layers=n_layers`` and
    random weights, save config.json + safetensors.
    """
    import torch
    import transformers

    if isinstance(hf_config, dict):
        hf_config = transformers.AutoConfig.for_model(**hf_config)
    cfg = hf_config.__class__.from_dict(hf_config.to_dict())
    cfg.num_hidden_layers = n_layers
    for k, v in (config_overrides or {}).items():
        setattr(cfg, k, v)
    torch.manual_seed(seed)
    model = transformers.AutoModelForCausalLM.from_config(cfg)
    os.makedirs(out_dir, exist_ok=True)
    model.save_pretrained(out_dir, safe_serialization=True)
    return out_dir


# ---------------------------------------------------------------------------
# Serving-artifact param-tree serialization (quantized / converted weights)
# ---------------------------------------------------------------------------
#
# ≈ reference quantized-checkpoint generation + pre-sharded weight save
# (`models/application_base.py:744-797`, `:240-265`): the CONVERTED serving
# layout (post HF rewrite, post weight quantization) is persisted so a second
# process start skips the HF ingest + quantize entirely. Format: a raw
# concatenated payload (`weights.bin`) plus a JSON manifest carrying key paths,
# dtypes and shapes — dependency-free and exact for ml_dtypes payloads
# (bfloat16 / float8) that .npy/.npz round-trip as raw void types.

ARTIFACT_MANIFEST = "weights.manifest.json"
ARTIFACT_PAYLOAD = "weights.bin"


def _artifact_dtype(arr: np.ndarray) -> str:
    return arr.dtype.name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _flatten_param_tree(tree, prefix=""):
    """Depth-first (key-sorted) flatten of a nested-dict param tree.

    None leaves are yielded as-is (recorded in the manifest with dtype "none")
    so the save/load round-trip preserves the tree SHAPE exactly — a family
    whose tree carries optional None entries must get them back on warm start."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _flatten_param_tree(tree[k], f"{prefix}{k}/")
    elif tree is None:
        yield prefix[:-1], None
    else:
        yield prefix[:-1], np.asarray(tree)


def save_param_tree(directory: str, params) -> str:
    """Serialize a (possibly quantized) host param pytree to ``directory``."""
    os.makedirs(directory, exist_ok=True)
    manifest = []
    offset = 0
    with open(os.path.join(directory, ARTIFACT_PAYLOAD), "wb") as payload:
        for key, arr in _flatten_param_tree(params):
            if arr is None:
                manifest.append({"key": key, "dtype": "none", "shape": [],
                                 "offset": offset, "nbytes": 0})
                continue
            arr = np.ascontiguousarray(arr)
            if arr.dtype.kind not in "fiub" and arr.dtype.name not in (
                    "bfloat16", "float8_e4m3fn", "float8_e5m2", "float8_e4m3"):
                raise ValueError(f"cannot serialize {key} with dtype {arr.dtype}")
            data = arr.tobytes()
            payload.write(data)
            manifest.append({"key": key, "dtype": _artifact_dtype(arr),
                             "shape": list(arr.shape), "offset": offset,
                             "nbytes": len(data)})
            offset += len(data)
    from ..ops.w4 import W4_PACK_VERSION

    with open(os.path.join(directory, ARTIFACT_MANIFEST), "w") as f:
        # record the int4 packed-layout version: q4 payloads from a different
        # packing decode silently wrong, so loaders must be able to refuse
        json.dump({"w4_pack_version": W4_PACK_VERSION, "entries": manifest}, f)
    return directory


def load_param_tree(directory: str):
    """Load a param pytree saved by :func:`save_param_tree` (memory-mapped)."""
    from ..ops.w4 import W4_PACK_VERSION

    with open(os.path.join(directory, ARTIFACT_MANIFEST)) as f:
        manifest = json.load(f)
    if isinstance(manifest, dict):
        ver = manifest.get("w4_pack_version")
        manifest = manifest["entries"]
    else:                               # legacy list-form manifest (pre-int4)
        ver = None
    if any(e["key"].endswith("/q4") for e in manifest) and ver != W4_PACK_VERSION:
        raise ValueError(
            f"artifact int4 pack version {ver} != current {W4_PACK_VERSION} — "
            "re-save the artifacts from the source checkpoint (the packed "
            "nibble layout changed; old payloads would decode silently wrong)")
    payload = np.memmap(os.path.join(directory, ARTIFACT_PAYLOAD), dtype=np.uint8,
                        mode="r")
    tree: Dict[str, Any] = {}
    for ent in manifest:
        if ent["dtype"] == "none":
            arr = None
        else:
            dt = _resolve_dtype(ent["dtype"])
            raw = payload[ent["offset"] : ent["offset"] + ent["nbytes"]]
            arr = raw.view(dt).reshape(ent["shape"])
        node = tree
        parts = ent["key"].split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree
