"""Tensor capture (extra graph outputs) and tensor replacement (inject goldens).

≈ reference tensor capture (`models/model_base.py:1076-1182`, `TensorCaptureConfig`
`models/config.py:1080-1128`) and tensor replacement (`TensorReplacementConfig`
`models/config.py:1131-1161`, `utils/tensor_replacement/registry.py`). TPU redesign:

The functional model calls ``tap(name, value)`` at known points ("embed",
"hidden_stack", "final_hidden", "logits"). Outside capture mode the tap is an identity
with zero overhead. Under ``capture(...)`` the model is re-traced (the application
builds a dedicated jit), taps record their values as extra outputs, and replacement
taps return the injected golden instead — the divergence-isolation workflow the
reference implements with extra graph outputs and mid-graph injection.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, Iterable, Optional, Sequence

_ACTIVE: contextvars.ContextVar[Optional["CaptureState"]] = contextvars.ContextVar(
    "tensor_capture_state", default=None)

# tap points the base model exposes (model families may tap more)
KNOWN_TAPS = ("embed", "hidden_stack", "final_hidden", "logits")
# taps whose return value feeds downstream compute (replacement-capable);
# "hidden_stack" is capture-only — it is emitted AFTER the layer scan consumed it
REPLACEABLE_TAPS = ("embed", "final_hidden", "logits")


class CaptureState:
    def __init__(self, names: Sequence[str],
                 replacements: Optional[Dict[str, Any]] = None):
        self.names = tuple(names)
        self.replacements = dict(replacements or {})
        for name in self.replacements:
            if name not in REPLACEABLE_TAPS:
                raise ValueError(
                    f"tap {name!r} is capture-only; replacements are supported at "
                    f"{REPLACEABLE_TAPS}")
        self.captured: Dict[str, Any] = {}

    def wants(self, name: str) -> bool:
        return name in self.names


def tap(name: str, value):
    """Model-side instrumentation point: identity unless capture is active."""
    st = _ACTIVE.get()
    if st is None:
        return value
    if name in st.replacements:
        import jax.numpy as jnp

        golden = jnp.asarray(st.replacements[name])
        if golden.shape != value.shape:
            raise ValueError(
                f"replacement for {name!r} has shape {golden.shape} but the tap "
                f"carries the PADDED shape {value.shape} (pad the golden to the "
                f"compiled batch/bucket)")
        value = golden.astype(value.dtype)
    if st.wants(name):
        st.captured[name] = value
    return value


@contextlib.contextmanager
def capture(names: Iterable[str] = KNOWN_TAPS,
            replacements: Optional[Dict[str, Any]] = None):
    """Activate taps for the duration of a trace; yields the CaptureState whose
    ``captured`` dict fills in during tracing (entries are tracers — return them from
    the traced function to materialize)."""
    st = CaptureState(tuple(names), replacements)
    token = _ACTIVE.set(st)
    try:
        yield st
    finally:
        _ACTIVE.reset(token)
