"""Device-resident telemetry carry: a fixed-shape counter block accumulated
IN-GRAPH by every continuous-batching dispatch kind.

Why: host-side telemetry (utils/metrics.py) observes the runner at commit
time, but depth-N dispatch-ahead already makes host step records lag the
device by ``async_depth`` chunks, and the planned ``lax.while_loop``
device-resident serving loop (ROADMAP open item 2) removes the per-step host
boundary entirely. The carry keeps the counters WITH the computation: a small
``(CARRY_LEN,)`` int32 vector threaded as a donated/aliased operand through
every jitted serving step, updated with in-graph adds, and drained to the
host only at sync points the runner already pays (the oldest-chunk commit /
pipeline flush) — zero new host syncs, and the analysis/ auditor machine-
checks the carry's aliasing and host-sync freedom like any cache operand
(``audited_jit(carry_args=("telem",))``).

Exactness contract: the token/eos/occupancy counters REPLAY the host's
commit rules in-graph (budget and eos stops, ``runtime/speculation.commit_row``
semantics for spec windows), so once the dispatch pipeline flushes the drained
counters equal the host event-log recompute exactly — the property
tests/test_device_telemetry.py pins across plain/spec/mixed/async paths.

Counter layout (int32; document any change in docs/OBSERVABILITY.md):

==================  =========================================================
``tokens``          tokens committed by decode/spec/mixed iterations, under
                    the host's exact budget/eos replay (seed tokens separate)
``spec_accepted``   tokens committed by speculative acceptance (subset of
                    ``tokens``; == ``tokens`` in pure-spec serving)
``spec_cells``      live (row, iteration) cells in spec chunks — the
                    acceptance-histogram count denominator
``occupancy``       sum of live rows over decode iterations / spec cells
                    (== ``tokens`` in non-spec serving, == ``spec_cells`` in
                    spec serving)
``kv_writes``       KV cache slots written (paged: valid slot-mapping
                    entries; dense: live-row writes)
``kv_blocks``       paged blocks newly entered (a valid slot at a block's
                    first position)
``eos``             rows stopped by emitting their eos token
``prefill_tokens``  prompt tokens written by insert windows / mixed chunk rows
``seed_tokens``     first tokens sampled at prompt completion that the host
                    emits (flag-gated: resumed re-inserts pass 0)
``megastep_iters``  inner steps executed by device-resident megastep loops
                    (the ``lax.while_loop`` serving path, ISSUE-10: per-inner-
                    step progress is otherwise invisible to the host until the
                    megastep's one sync — once the pipeline flushes this
                    equals the host's committed-iteration counter exactly)
``step:<kind>``     dispatches per step kind (decode / spec_chunk / mixed /
                    insert / insert_window / tier_readmit — the host-RAM KV
                    tier's block re-admission scatter, serving/kv_tiering.py —
                    / kv_handoff — the pool-to-pool live KV block transfer
                    scatter, serving/pools.py — / megastep — the
                    device-resident while_loop decode — / spec_megastep — the
                    while_loop draft-verify-commit chunk loop, ISSUE-19 —
                    / mixed_megastep — the scanned multi-window mixed
                    insert+decode step, ISSUE-19)
==================  =========================================================
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

__all__ = ["CARRY_LEN", "FIELDS", "KINDS", "init_carry", "to_dict",
           "decode_tick", "dense_kv_tick", "kv_tick", "prefill_tick",
           "seed_tick", "spec_tick", "megastep_iter_tick", "bump_kind"]

# named scalar counters, then one dispatch counter per step kind
FIELDS = ("tokens", "spec_accepted", "spec_cells", "occupancy", "kv_writes",
          "kv_blocks", "eos", "prefill_tokens", "seed_tokens",
          "megastep_iters")
KINDS = ("decode", "spec_chunk", "mixed", "insert", "insert_window",
         "tier_readmit", "kv_handoff", "megastep", "spec_megastep",
         "mixed_megastep")

IDX_TOKENS = 0
IDX_SPEC_ACCEPTED = 1
IDX_SPEC_CELLS = 2
IDX_OCCUPANCY = 3
IDX_KV_WRITES = 4
IDX_KV_BLOCKS = 5
IDX_EOS = 6
IDX_PREFILL = 7
IDX_SEED = 8
IDX_MEGA_ITERS = 9
KIND_BASE = len(FIELDS)
CARRY_LEN = KIND_BASE + len(KINDS)

KIND_DECODE = KINDS.index("decode")
KIND_SPEC = KINDS.index("spec_chunk")
KIND_MIXED = KINDS.index("mixed")
KIND_INSERT = KINDS.index("insert")
KIND_INSERT_WINDOW = KINDS.index("insert_window")
KIND_TIER_READMIT = KINDS.index("tier_readmit")
KIND_KV_HANDOFF = KINDS.index("kv_handoff")
KIND_MEGASTEP = KINDS.index("megastep")
KIND_SPEC_MEGASTEP = KINDS.index("spec_megastep")
KIND_MIXED_MEGASTEP = KINDS.index("mixed_megastep")


def init_carry():
    """Fresh zeroed carry block (host- or device-side)."""
    return jnp.zeros((CARRY_LEN,), jnp.int32)


def to_dict(arr) -> Dict[str, int]:
    """Host-side view of a drained carry: named counters + per-kind step
    counts + the derived totals the tests/stats() read."""
    arr = np.asarray(arr).astype(np.int64)
    out = {name: int(arr[i]) for i, name in enumerate(FIELDS)}
    out["steps"] = {k: int(arr[KIND_BASE + i]) for i, k in enumerate(KINDS)
                    if arr[KIND_BASE + i]}
    out["tokens_total"] = out["tokens"] + out["seed_tokens"]
    return out


# --------------------------------------------------------------- in-graph ticks
# All helpers are pure jnp (trace-safe), take and return the carry vector, and
# cost a handful of scalar reductions + dynamic-update-slices per call — noise
# next to a decode iteration's weight stream.
def decode_tick(telem, alive, nxt, eos_ids):
    """One chained decode iteration: ``alive`` rows each commit one token
    (``nxt``); a live row emitting its eos stops — the exact mirror of the
    host's per-token commit/stop replay (ContinuousBatchingRunner._commit)."""
    n = jnp.sum(alive)
    telem = telem.at[IDX_TOKENS].add(n)
    telem = telem.at[IDX_OCCUPANCY].add(n)
    return telem.at[IDX_EOS].add(jnp.sum(alive & (nxt == eos_ids)))


def kv_tick(telem, slots, block_size: int):
    """Paged KV writes from a slot mapping (-1 = dropped write): valid slots
    written, plus blocks newly entered (slot at a block's first position)."""
    valid = slots >= 0
    telem = telem.at[IDX_KV_WRITES].add(jnp.sum(valid))
    return telem.at[IDX_KV_BLOCKS].add(
        jnp.sum(valid & (slots % block_size == 0)))


def dense_kv_tick(telem, alive):
    """Dense-cache decode writes: one slot per live row (frozen rows re-write
    their pinned position with identical bytes — not counted)."""
    return telem.at[IDX_KV_WRITES].add(jnp.sum(alive))


def prefill_tick(telem, slots, block_size: int):
    """One paged insert window / mixed chunk row set: prompt tokens written =
    valid slot-mapping entries (padding carries -1)."""
    telem = telem.at[IDX_PREFILL].add(jnp.sum(slots >= 0))
    return kv_tick(telem, slots, block_size)


def seed_tick(telem, emit):
    """Prompt-final sampled token: ``emit`` is the HOST-known 0/1 flag (a
    resumed/preempted re-insert discards its seed, so the host passes 0)."""
    return telem.at[IDX_SEED].add(emit)


def spec_tick(telem, alive_t, budget, out_toks, n, eos_ids):
    """One fused-speculation iteration, replaying ``commit_row`` exactly.

    ``alive_t``/``budget`` are the COUNTING replay state (the device's real
    alive mask ignores per-row budgets — the host truncates at commit; here
    we truncate in-graph so the counters match the host): a row commits
    ``min(n + 1, budget, first-eos-position + 1)`` tokens, dies on budget
    exhaustion or an eos that lands within its committed window. Returns
    ``(telem, alive_t, budget)`` for the next iteration."""
    width = out_toks.shape[1]
    take = n + 1
    idx = jnp.arange(width, dtype=jnp.int32)[None, :]
    is_eos = (out_toks == eos_ids[:, None]) & (idx < take[:, None])
    eos_pos = jnp.min(jnp.where(is_eos, idx, width), axis=1)
    committed = jnp.minimum(jnp.minimum(take, budget), eos_pos + 1)
    committed = jnp.where(alive_t, committed, 0)
    eos_hit = alive_t & (eos_pos + 1 == committed)
    cells = jnp.sum(alive_t)
    total = jnp.sum(committed)
    telem = telem.at[IDX_TOKENS].add(total)
    telem = telem.at[IDX_SPEC_ACCEPTED].add(total)
    telem = telem.at[IDX_SPEC_CELLS].add(cells)
    telem = telem.at[IDX_OCCUPANCY].add(cells)
    telem = telem.at[IDX_EOS].add(jnp.sum(eos_hit))
    budget = budget - committed
    return telem, alive_t & (budget > 0) & ~eos_hit, budget


def megastep_iter_tick(telem):
    """One executed inner step of a device-resident megastep while_loop —
    ticked INSIDE the loop body (early exits leave the untaken iterations
    uncounted, exactly like the host's committed-iteration mirror)."""
    return telem.at[IDX_MEGA_ITERS].add(1)


def bump_kind(telem, kind_id: int):
    """Count one dispatch of a (trace-time static) step kind."""
    return telem.at[KIND_BASE + kind_id].add(1)
