"""Contrib model hub registry (≈ reference `contrib/models/` community ports).

Importing this module registers every contrib family with the main model registry,
so `get_model_cls(model_type)` and the CLI resolve them like first-class families.
"""

from neuronx_distributed_inference_tpu.models import register_model

CONTRIB_MODELS = {
    "gpt2": "contrib.models.gpt2.src.modeling_gpt2:GPT2ForCausalLM",
    "opt": "contrib.models.opt.src.modeling_opt:OPTForCausalLM",
    "gpt_neox": "contrib.models.pythia.src.modeling_pythia:PythiaForCausalLM",
    "phi": "contrib.models.phi.src.modeling_phi:PhiForCausalLM",
    "phi3": "contrib.models.phi3.src.modeling_phi3:Phi3ForCausalLM",
    "starcoder2":
        "contrib.models.starcoder2.src.modeling_starcoder2:Starcoder2ForCausalLM",
    "falcon": "contrib.models.falcon.src.modeling_falcon:FalconForCausalLM",
    "bloom": "contrib.models.bloom.src.modeling_bloom:BloomForCausalLM",
    "mpt": "contrib.models.mpt.src.modeling_mpt:MptForCausalLM",
    "stablelm": "contrib.models.stablelm.src.modeling_stablelm:StableLmForCausalLM",
    "gemma": "contrib.models.gemma.src.modeling_gemma:GemmaForCausalLM",
    "biogpt": "contrib.models.biogpt.src.modeling_biogpt:BioGptForCausalLM",
    "granite": "contrib.models.granite.src.modeling_granite:GraniteForCausalLM",
    "cohere": "contrib.models.cohere.src.modeling_cohere:CohereForCausalLM",
    "glm": "contrib.models.glm.src.modeling_glm:GlmForCausalLM",
    "gemma2": "contrib.models.gemma2.src.modeling_gemma2:Gemma2ForCausalLM",
    "phimoe": "contrib.models.phimoe.src.modeling_phimoe:PhimoeForCausalLM",
    "recurrent_gemma": "contrib.models.recurrentgemma.src.modeling_recurrentgemma:RecurrentGemmaForCausalLM",
    "lfm2": "contrib.models.lfm2.src.modeling_lfm2:Lfm2ForCausalLM",
    "llava": "contrib.models.llava.src.modeling_llava:LlavaForConditionalGeneration",
    "helium": "contrib.models.helium.src.modeling_helium:HeliumForCausalLM",
    "qwen2_moe": "contrib.models.qwen2_moe.src.modeling_qwen2_moe:Qwen2MoeForCausalLM",
    "olmo2": "contrib.models.olmo2.src.modeling_olmo2:Olmo2ForCausalLM",
    "nemotron": "contrib.models.nemotron.src.modeling_nemotron:NemotronForCausalLM",
    "cohere2": "contrib.models.cohere2.src.modeling_cohere2:Cohere2ForCausalLM",
    "smollm3": "contrib.models.smollm3.src.modeling_smollm3:SmolLM3ForCausalLM",
    "granitemoe": "contrib.models.granitemoe.src.modeling_granitemoe:GraniteMoeForCausalLM",
    "ernie4_5": "contrib.models.ernie4_5.src.modeling_ernie4_5:Ernie45ForCausalLM",
    "exaone4": "contrib.models.exaone4.src.modeling_exaone4:Exaone4ForCausalLM",
    "gptj": "contrib.models.gptj.src.modeling_gptj:GPTJForCausalLM",
    "gpt_neo": "contrib.models.gpt_neo.src.modeling_gpt_neo:GPTNeoForCausalLM",
    "codegen": "contrib.models.codegen.src.modeling_codegen:CodeGenForCausalLM",
    "olmo": "contrib.models.olmo.src.modeling_olmo:OlmoForCausalLM",
    "olmoe": "contrib.models.olmoe.src.modeling_olmoe:OlmoeForCausalLM",
    "mamba": "contrib.models.mamba.src.modeling_mamba:MambaForCausalLM",
    "jamba": "contrib.models.jamba.src.modeling_jamba:JambaForCausalLM",
    "persimmon": "contrib.models.persimmon.src.modeling_persimmon:PersimmonForCausalLM",
    "xglm": "contrib.models.xglm.src.modeling_xglm:XGLMForCausalLM",
    "seed_oss": "contrib.models.seed_oss.src.modeling_seed_oss:SeedOssForCausalLM",
    "minimax": "contrib.models.minimax.src.modeling_minimax:MiniMaxForCausalLM",
    "apertus": "contrib.models.apertus.src.modeling_apertus:ApertusForCausalLM",
    "mamba2": "contrib.models.mamba2.src.modeling_mamba2:Mamba2ForCausalLM",
    "falcon_h1": "contrib.models.falcon_h1.src.modeling_falcon_h1:FalconH1ForCausalLM",
    "glm4": "contrib.models.glm4.src.modeling_glm4:Glm4ForCausalLM",
    "gpt_bigcode": "contrib.models.gpt_bigcode.src.modeling_gpt_bigcode:GPTBigCodeForCausalLM",
    "granitemoeshared": "contrib.models.granitemoeshared.src.modeling_granitemoeshared:GraniteMoeSharedForCausalLM",
    "falcon_mamba": "contrib.models.falcon_mamba.src.modeling_falcon_mamba:FalconMambaForCausalLM",
    "bamba": "contrib.models.bamba.src.modeling_bamba:BambaForCausalLM",
    "vaultgemma": "contrib.models.vaultgemma.src.modeling_vaultgemma:VaultGemmaForCausalLM",
    "granitemoehybrid": "contrib.models.granitemoehybrid.src.modeling_granitemoehybrid:GraniteMoeHybridForCausalLM",
    "openai-gpt": "contrib.models.openai_gpt.src.modeling_openai_gpt:OpenAIGPTForCausalLM",
    "moonshine": "contrib.models.moonshine.src.modeling_moonshine:MoonshineForConditionalGeneration",
    "zamba2": "contrib.models.zamba2.src.modeling_zamba2:Zamba2ForCausalLM",
    "zamba": "contrib.models.zamba.src.modeling_zamba:ZambaForCausalLM",
    "arcee": "contrib.models.arcee.src.modeling_arcee:ArceeForCausalLM",
    "olmo3": "contrib.models.olmo3.src.modeling_olmo3:Olmo3ForCausalLM",
    "hunyuan_v1_dense":
        "contrib.models.hunyuan.src.modeling_hunyuan:HunYuanDenseForCausalLM",
    "internlm3":
        "contrib.models.internlm3.src.modeling_internlm3:InternLM3ForCausalLM",
    "orion": "contrib.models.orion.src.modeling_orion:OrionForCausalLM",
    "minicpm": "contrib.models.minicpm.src.modeling_minicpm:MiniCPMForCausalLM",
    "minicpm4":
        "contrib.models.minicpm.src.modeling_minicpm:MiniCPMForCausalLM",
    "afmoe": "contrib.models.trinity.src.modeling_trinity:TrinityForCausalLM",
    # outer gemma3 VLM config (text_config + vision_config); the bare-text
    # model_type "gemma3_text" stays on the core text class
    "gemma3": ("contrib.models.gemma3_vision.src.modeling_gemma3_vision:"
               "Gemma3ForConditionalGeneration"),
    "gemma3_vision": ("contrib.models.gemma3_vision.src.modeling_gemma3_vision:"
                      "Gemma3ForConditionalGeneration"),
    "janus": "contrib.models.janus.src.modeling_janus:JanusForConditionalGeneration",
    "ovis2": "contrib.models.ovis2.src.modeling_ovis2:Ovis2ForConditionalGeneration",
    "idefics":
        "contrib.models.idefics.src.modeling_idefics:IdeficsForVisionText2Text",
    "qwen2_5_omni": ("contrib.models.qwen2_5_omni.src.modeling_qwen2_5_omni:"
                     "Qwen25OmniThinkerForCausalLM"),
    "qwen2_5_omni_thinker": (
        "contrib.models.qwen2_5_omni.src.modeling_qwen2_5_omni:"
        "Qwen25OmniThinkerForCausalLM"),
}

for model_type, path in CONTRIB_MODELS.items():
    register_model(model_type, path)
