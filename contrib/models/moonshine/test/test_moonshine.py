"""moonshine parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/moonshine/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_moonshine_parity():
    """Moonshine ASR (whisper-style enc-dec contrib): raw-waveform conv stem,
    rotary encoder/decoder self-attention, rope-free cross-attention,
    gated-silu decoder MLP. Logit + greedy parity vs HF."""
    from transformers import (MoonshineConfig,
                              MoonshineForConditionalGeneration as HFMoon)

    from contrib.models.moonshine.src.modeling_moonshine import (
        MoonshineForConditionalGeneration)

    cfg = MoonshineConfig(vocab_size=256, hidden_size=32, intermediate_size=64,
                          encoder_num_hidden_layers=2,
                          decoder_num_hidden_layers=2,
                          encoder_num_attention_heads=4,
                          decoder_num_attention_heads=4,
                          encoder_num_key_value_heads=4,
                          decoder_num_key_value_heads=4,
                          max_position_embeddings=128,
                          decoder_start_token_id=1, eos_token_id=2,
                          pad_token_id=0)
    torch.manual_seed(0)
    hf = HFMoon(cfg).eval()

    config = MoonshineForConditionalGeneration.get_config_cls()(
        _tpu_cfg(), load_config=load_pretrained_config(cfg.to_dict()))
    app = MoonshineForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app.load_from_state_dict(state)

    rng = np.random.default_rng(0)
    audio = rng.standard_normal((2, 4000)).astype(np.float32) * 0.1
    # -1 sentinel disables EOS on both sides (same trick as test_whisper)
    out = app.generate(audio, max_new_tokens=8, eos_token_id=-1)

    with torch.no_grad():
        hf_out = hf.generate(input_values=torch.tensor(audio),
                             max_new_tokens=8, do_sample=False,
                             eos_token_id=-1, pad_token_id=0)
    np.testing.assert_array_equal(out, hf_out.numpy())
