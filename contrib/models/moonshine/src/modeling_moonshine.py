"""Moonshine (UsefulSensors streaming ASR) on the TPU framework (contrib
port).

≈ reference whisper integration pattern (separate encoder/decoder instances)
applied to Moonshine: a RAW-WAVEFORM conv stem (conv k=127 s=64 → tanh →
1-group GroupNorm → two gelu convs) instead of whisper's mel frontend, rotary
(partial, theta-scaled by rotary width) self-attention in BOTH encoder and
decoder, rope-free cross-attention with precomputed encoder K/V, weight-only
LayerNorms, bias-free attention projections, and a gated-silu decoder MLP
(fc1 → [hidden | gate] → silu(gate)·hidden → fc2). Greedy loop and KV-cache
layout mirror models/whisper. Audio batches must be unpadded (no
attention-mask support), matching the reference whisper port's contract.
"""

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import (InferenceConfig,
                                                      TpuConfig)
from neuronx_distributed_inference_tpu.modules import kvcache
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import layer_norm


def _ln(x, w, eps=1e-5):
    return layer_norm(x, w, jnp.zeros_like(w), eps=eps)


def _rot_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _rope(q, k, cos, sin):
    """Partial rotary over the first cos.shape[-1] dims (HF moonshine
    `apply_rotary_pos_emb`: rotary_dim taken from the cos table width)."""
    rd = cos.shape[-1]
    cos, sin = cos[None, None, :, :], sin[None, None, :, :]
    qr, qp = q[..., :rd].astype(jnp.float32), q[..., rd:]
    kr, kp = k[..., :rd].astype(jnp.float32), k[..., rd:]
    qr = qr * cos + _rot_half(qr) * sin
    kr = kr * cos + _rot_half(kr) * sin
    q = jnp.concatenate([qr.astype(q.dtype), qp], axis=-1)
    k = jnp.concatenate([kr.astype(k.dtype), kp], axis=-1)
    return q, k


def _cos_sin(inv_freq, positions):
    freqs = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def _heads(x, heads):
    b, s, hdim = x.shape
    return x.reshape(b, s, heads, hdim // heads).transpose(0, 2, 1, 3)


def encode(params, input_values, *, heads: int):
    """(B, T_audio) raw waveform -> (B, T', H) encoder states."""
    dn = ("NCH", "OIH", "NCH")
    x = input_values[:, None, :]                            # (B, 1, T)
    x = jax.lax.conv_general_dilated(x, params["conv1_w"], (64,), "VALID",
                                     dimension_numbers=dn)
    x = jnp.tanh(x)
    # GroupNorm(1 group): normalize over (C, T) jointly, per-channel affine
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.var(x32, axis=(1, 2), keepdims=True)
    x32 = (x32 - mu) * jax.lax.rsqrt(var + 1e-5)
    x = (x32 * params["gn_w"][None, :, None]
         + params["gn_b"][None, :, None]).astype(x.dtype)
    x = jax.lax.conv_general_dilated(x, params["conv2_w"], (3,), "VALID",
                                     dimension_numbers=dn)
    x = jax.nn.gelu(x + params["conv2_b"][None, :, None], approximate=False)
    x = jax.lax.conv_general_dilated(x, params["conv3_w"], (2,), "VALID",
                                     dimension_numbers=dn)
    x = jax.nn.gelu(x + params["conv3_b"][None, :, None], approximate=False)
    h = x.transpose(0, 2, 1)                                # (B, T', H)

    cos, sin = _cos_sin(params["inv_freq"], jnp.arange(h.shape[1]))

    def body(hid, lp):
        hn = _ln(hid, lp["ln1"])
        q = _heads(hn @ lp["attn_wq"], heads)
        k = _heads(hn @ lp["attn_wk"], heads)
        v = _heads(hn @ lp["attn_wv"], heads)
        q, k = _rope(q, k, cos, sin)
        a = attend(q, k, v)
        a = a.transpose(0, 2, 1, 3).reshape(hid.shape)
        hid = hid + a @ lp["attn_wo"]
        hn = _ln(hid, lp["ln2"])
        hid = hid + (jax.nn.gelu(hn @ lp["fc1"] + lp["b1"], approximate=False)
                     @ lp["fc2"] + lp["b2"])
        return hid, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return _ln(h, params["ln_post"])


def compute_cross_kv(dec_params, enc_states, heads: int):
    """Precompute per-decoder-layer rope-free cross K/V from the encoder."""
    def one(lp):
        k = _heads(enc_states @ lp["xattn_wk"], heads)
        v = _heads(enc_states @ lp["xattn_wv"], heads)
        return k, v

    return jax.vmap(one)(dec_params["layers"])


def decoder_forward(params, input_ids, position_ids, cache,
                    decode_bucket: Optional[int], *, heads: int):
    b, t = input_ids.shape
    pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]
    h = jnp.take(params["embed"], input_ids, axis=0)

    if decode_bucket is None:
        mask = pos_grid[:, None, :, None] >= pos_grid[:, None, None, :]
    else:
        kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
        mask = kv_pos <= pos_grid[:, None, :, None]
    # rope tables are position-dependent per row; decode is single-position
    cos, sin = _cos_sin(params["inv_freq"], pos_grid[0])

    def body(carry_h, xs):
        lp, kc, vc, xk, xv = xs
        hn = _ln(carry_h, lp["ln1"])
        q = _heads(hn @ lp["attn_wq"], heads)
        k = _heads(hn @ lp["attn_wk"], heads)
        v = _heads(hn @ lp["attn_wv"], heads)
        q, k = _rope(q, k, cos, sin)
        if decode_bucket is None:
            kc = kvcache.write_prefill(kc, k)
            vc = kvcache.write_prefill(vc, v)
            k_att, v_att = k, v
        else:
            kc = kvcache.write_decode(kc, k, position_ids)
            vc = kvcache.write_decode(vc, v, position_ids)
            k_att = kvcache.read_bucket(kc, decode_bucket)
            v_att = kvcache.read_bucket(vc, decode_bucket)
        a = attend(q, k_att, v_att, mask=mask)
        carry_h = carry_h + a.transpose(0, 2, 1, 3).reshape(b, t, -1) @ lp["attn_wo"]

        hn = _ln(carry_h, lp["xln"])
        q = _heads(hn @ lp["xattn_wq"], heads)
        xo = attend(q, xk, xv)
        carry_h = carry_h + xo.transpose(0, 2, 1, 3).reshape(b, t, -1) @ lp["xattn_wo"]

        hn = _ln(carry_h, lp["ln2"])
        inter = hn @ lp["fc1"] + lp["b1"]
        hid, gate = jnp.split(inter, 2, axis=-1)
        carry_h = carry_h + (jax.nn.silu(gate) * hid) @ lp["fc2"] + lp["b2"]
        return carry_h, (kc, vc)

    xs = (params["layers"], cache["k"], cache["v"], cache["xk"], cache["xv"])
    h, (k_new, v_new) = jax.lax.scan(body, h, xs)
    h = _ln(h, params["ln_post"])
    logits = (h @ params["proj_out"]).astype(jnp.float32)
    return logits, dict(cache, k=k_new, v=v_new)


class MoonshineInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "intermediate_size",
                           "encoder_num_hidden_layers",
                           "decoder_num_hidden_layers",
                           "encoder_num_attention_heads",
                           "decoder_num_attention_heads", "vocab_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0),
                              ("partial_rotary_factor", 0.9),
                              ("decoder_start_token_id", 1),
                              ("eos_token_id", 2)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = (self.hidden_size
                             // self.decoder_num_attention_heads)
        for a, b in (("encoder_num_key_value_heads",
                      "encoder_num_attention_heads"),
                     ("decoder_num_key_value_heads",
                      "decoder_num_attention_heads")):
            if getattr(self, a, None) not in (None, getattr(self, b)):
                raise ValueError(f"Moonshine GQA ({a}) is not ported — "
                                 "released checkpoints use MHA")


class MoonshineForConditionalGeneration:
    """Raw-audio encoder + token decoder (whisper-style application)."""

    def __init__(self, model_path: Optional[str],
                 config: MoonshineInferenceConfig):
        self.model_path = model_path
        self.config = config
        self.tpu_config: TpuConfig = config.tpu_config
        self.enc_params = None
        self.dec_params = None
        enc_heads = config.encoder_num_attention_heads
        dec_heads = config.decoder_num_attention_heads
        self._encode = jax.jit(functools.partial(encode, heads=enc_heads))
        self._cross_kv = jax.jit(
            functools.partial(compute_cross_kv, heads=dec_heads))

        def _prefill(dec_params, input_ids, position_ids, cache):
            return decoder_forward(dec_params, input_ids, position_ids, cache,
                                   None, heads=dec_heads)

        def _decode_chunk(dec_params, tok0, position_ids, cache, decode_bucket,
                          num_steps):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = decoder_forward(dec_params, tok[:, None], pos,
                                                cache, decode_bucket,
                                                heads=dec_heads)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, cache), nxt

            (_, _, cache), toks = jax.lax.scan(
                body, (tok0, position_ids, cache), None, length=num_steps)
            return toks.T, cache

        self._prefill = jax.jit(_prefill, donate_argnums=(3,))
        self._decode_chunk = jax.jit(_decode_chunk, donate_argnums=(3,),
                                     static_argnames=("decode_bucket",
                                                      "num_steps"))

    @classmethod
    def get_config_cls(cls):
        return MoonshineInferenceConfig

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        rd = int(config.head_dim * float(config.partial_rotary_factor))
        return (1.0 / float(config.rope_theta)
                ** (np.arange(0, rd, 2, dtype=np.float32) / rd))

    @classmethod
    def convert_hf_state_dict(cls, state_dict,
                              config) -> Tuple[Dict, Dict]:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        def attn(prefix, out_prefix, out):
            out.update({
                out_prefix + "wq": lin_t(prefix + "q_proj.weight"),
                out_prefix + "wk": lin_t(prefix + "k_proj.weight"),
                out_prefix + "wv": lin_t(prefix + "v_proj.weight"),
                out_prefix + "wo": lin_t(prefix + "o_proj.weight"),
            })

        def stack(dicts):
            return {k: np.stack([x[k] for x in dicts]) for k in dicts[0]}

        inv_freq = cls.inv_freq_from_config(config)
        enc_layers = []
        for i in range(config.encoder_num_hidden_layers):
            p = f"model.encoder.layers.{i}."
            lp = {
                "ln1": get(p + "input_layernorm.weight"),
                "ln2": get(p + "post_attention_layernorm.weight"),
                "fc1": lin_t(p + "mlp.fc1.weight"),
                "b1": get(p + "mlp.fc1.bias"),
                "fc2": lin_t(p + "mlp.fc2.weight"),
                "b2": get(p + "mlp.fc2.bias"),
            }
            attn(p + "self_attn.", "attn_", lp)
            enc_layers.append(lp)
        enc = {
            "conv1_w": get("model.encoder.conv1.weight"),
            "gn_w": get("model.encoder.groupnorm.weight"),
            "gn_b": get("model.encoder.groupnorm.bias"),
            "conv2_w": get("model.encoder.conv2.weight"),
            "conv2_b": get("model.encoder.conv2.bias"),
            "conv3_w": get("model.encoder.conv3.weight"),
            "conv3_b": get("model.encoder.conv3.bias"),
            "layers": stack(enc_layers),
            "ln_post": get("model.encoder.layer_norm.weight"),
            "inv_freq": inv_freq,
        }

        dec_layers = []
        for i in range(config.decoder_num_hidden_layers):
            p = f"model.decoder.layers.{i}."
            lp = {
                "ln1": get(p + "input_layernorm.weight"),
                "xln": get(p + "post_attention_layernorm.weight"),
                "ln2": get(p + "final_layernorm.weight"),
                "fc1": lin_t(p + "mlp.fc1.weight"),
                "b1": get(p + "mlp.fc1.bias"),
                "fc2": lin_t(p + "mlp.fc2.weight"),
                "b2": get(p + "mlp.fc2.bias"),
            }
            attn(p + "self_attn.", "attn_", lp)
            attn(p + "encoder_attn.", "xattn_", lp)
            dec_layers.append(lp)
        embed = get("model.decoder.embed_tokens.weight")
        dec = {
            "embed": embed,
            "layers": stack(dec_layers),
            "ln_post": get("model.decoder.norm.weight"),
            # tied checkpoints drop proj_out.weight from the serialized dict
            "proj_out": (lin_t("proj_out.weight")
                         if "proj_out.weight" in state_dict
                         else np.ascontiguousarray(embed.T)),
            "inv_freq": inv_freq,
        }
        return enc, dec

    def load_from_state_dict(self, state_dict) -> None:
        enc, dec = self.convert_hf_state_dict(state_dict, self.config)
        dtype = self.tpu_config.jax_dtype

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f" and last != "inv_freq":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        self.enc_params = jax.tree_util.tree_map_with_path(_put, enc)
        self.dec_params = jax.tree_util.tree_map_with_path(_put, dec)

    def load(self, model_path: Optional[str] = None) -> None:
        from neuronx_distributed_inference_tpu.utils import checkpoint as ckpt

        self.load_from_state_dict(
            ckpt.load_state_dict(model_path or self.model_path))

    @classmethod
    def from_pretrained(cls, model_path: str, tpu_config: TpuConfig):
        from neuronx_distributed_inference_tpu.config import (
            load_pretrained_config)

        config = MoonshineInferenceConfig(
            tpu_config, load_config=load_pretrained_config(model_path))
        app = cls(model_path, config)
        app.load()
        return app

    def _init_cache(self, b: int, t_enc: int):
        c = self.config
        heads = c.decoder_num_attention_heads
        d = c.hidden_size // heads
        L = c.decoder_num_hidden_layers
        S = self.tpu_config.seq_len
        dtype = self.tpu_config.jax_dtype
        return {
            "k": jnp.zeros((L, b, heads, S, d), dtype=dtype),
            "v": jnp.zeros((L, b, heads, S, d), dtype=dtype),
            "xk": jnp.zeros((L, b, heads, t_enc, d), dtype=dtype),
            "xv": jnp.zeros((L, b, heads, t_enc, d), dtype=dtype),
        }

    def generate(self, input_values: np.ndarray,
                 decoder_input_ids: Optional[np.ndarray] = None,
                 max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Greedy transcription of raw waveforms: (B, prompt + generated)."""
        if self.enc_params is None:
            raise RuntimeError("load weights before generate")
        audio = np.asarray(input_values, dtype=np.float32)
        b = audio.shape[0]
        if decoder_input_ids is None:
            decoder_input_ids = np.full(
                (b, 1), self.config.decoder_start_token_id, dtype=np.int32)
        ids = np.asarray(decoder_input_ids, dtype=np.int32)
        enc_states = self._encode(self.enc_params, audio)
        xk, xv = self._cross_kv(self.dec_params, enc_states)
        cache = self._init_cache(b, enc_states.shape[1])
        cache["xk"], cache["xv"] = xk, xv

        pos0 = np.zeros((b,), dtype=np.int32)
        logits, cache = self._prefill(self.dec_params, ids, pos0, cache)
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

        out = [ids, np.asarray(tok)[:, None]]
        n_done, pos = 1, ids.shape[1]
        chunk = max(1, self.tpu_config.decode_chunk_size)
        eos = (eos_token_id if eos_token_id is not None
               else self.config.eos_token_id)
        eos_done = np.zeros((b,), dtype=bool)
        while n_done < max_new_tokens:
            steps = min(chunk, max_new_tokens - n_done,
                        self.tpu_config.seq_len - pos)
            if steps <= 0:
                break
            positions = np.full((b,), pos, dtype=np.int32)
            bucket = min(self.tpu_config.seq_len,
                         1 << (pos + steps).bit_length())
            toks, cache = self._decode_chunk(self.dec_params, tok, positions,
                                             cache, decode_bucket=bucket,
                                             num_steps=steps)
            toks_np = np.asarray(toks)
            out.append(toks_np)
            tok = toks[:, -1]
            pos += steps
            n_done += steps
            if eos is not None:
                eos_done |= (toks_np == eos).any(axis=1)
                if eos_done.all():
                    break
        return np.concatenate(out, axis=1)
