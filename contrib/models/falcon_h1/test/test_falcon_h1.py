"""falcon_h1 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/falcon_h1/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def _falcon_h1_cfg(**over):
    from transformers import FalconH1Config

    kw = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, mamba_d_ssm=64, mamba_n_heads=8,
              mamba_d_head=8, mamba_n_groups=2, mamba_d_state=8,
              mamba_d_conv=4, mamba_expand=2, rope_theta=100000.0,
              attention_in_multiplier=0.5, attention_out_multiplier=1.5,
              ssm_in_multiplier=0.8, ssm_out_multiplier=1.2,
              ssm_multipliers=[0.5, 1.5, 0.7, 1.3, 0.9], key_multiplier=0.6,
              embedding_multiplier=2.0, lm_head_multiplier=0.3,
              mlp_multipliers=[0.9, 1.1], tie_word_embeddings=False,
              pad_token_id=0)
    kw.update(over)
    return FalconH1Config(**kw)


def test_falcon_h1_parity():
    """Falcon-H1: mamba2 SSD mixer and rope GQA attention run in PARALLEL on
    the same normed input per layer, with the full muP multiplier family
    (embedding, ssm in/out, zxbcdt mup vector, attention in/out, key, mlp
    gate/down, lm-head) — all set to non-trivial values here."""
    from transformers.models.falcon_h1.modeling_falcon_h1 import (
        FalconH1ForCausalLM as HFFalconH1)

    from contrib.models.falcon_h1.src.modeling_falcon_h1 import (
        FalconH1ForCausalLM)

    torch.manual_seed(0)
    hf = HFFalconH1(_falcon_h1_cfg()).eval()
    _run_parity(FalconH1ForCausalLM, hf, _falcon_h1_cfg(), atol=2e-3, rtol=1e-3)


def test_falcon_h1_gated_norm_variant():
    """mamba_rms_norm=True switches the mixer output gate to a grouped gated
    RMSNorm (norm-before-gate).

    Compares per-step decode logits against HF full-recompute (no cache):
    a random-init Falcon-H1 has near-uniform logits (top-1 gap ~0.01), where
    HF's own cached generate path flips argmax vs its uncached forward, so
    greedy-token equality against hf.generate is not a stable oracle here.
    """
    from transformers.models.falcon_h1.modeling_falcon_h1 import (
        FalconH1ForCausalLM as HFFalconH1)

    from contrib.models.falcon_h1.src.modeling_falcon_h1 import (
        FalconH1ForCausalLM)

    cfg = _falcon_h1_cfg(mamba_rms_norm=True)
    torch.manual_seed(1)
    hf = HFFalconH1(cfg).eval()

    config = FalconH1ForCausalLM.get_config_cls()(
        _tpu_cfg(), load_config=load_pretrained_config(cfg.to_dict()))
    app = FalconH1ForCausalLM(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))

    rng = np.random.default_rng(0)
    ids = rng.integers(1, 256, size=(2, 12)).astype(np.int64)
    out = app.generate(ids, max_new_tokens=4, return_logits=True)

    cur = torch.tensor(ids)
    with torch.no_grad():
        for step in range(4):
            hf_logits = hf(cur).logits[:, -1]
            np.testing.assert_allclose(out.logits[step], hf_logits.numpy(),
                                       atol=2e-3, rtol=1e-3)
            cur = torch.cat([cur, torch.tensor(out.tokens[:, step:step + 1],
                                               dtype=torch.long)], 1)
