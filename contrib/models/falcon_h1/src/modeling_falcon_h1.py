"""Falcon-H1 (TII mamba2/attention PARALLEL hybrid) on the TPU framework
(contrib port).

≈ reference `contrib/models/Falcon-H1-0.5B-Instruct/`. Every layer runs a
Mamba-2-style SSD mixer AND a rope GQA attention head-to-head on the SAME
normed input, sums the two branch outputs (each with its own multiplier), then
a gated MLP — plus Falcon-H1's muP-style multiplier family (embedding, ssm-in,
per-chunk zxbcdt mup vector, attention-in/out, key, mlp gate/down, lm-head).
The SSD prefill rides the same associative-scan redesign as
contrib/models/mamba2; the hybrid cache pytree carries per-layer conv tails +
fp32 SSM states next to the attention KV stacks.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class FalconH1ArchArgs(ModelArchArgs):
    d_ssm: int = 0
    d_state: int = 256
    d_conv: int = 4
    ssd_heads: int = 128
    ssd_head_dim: int = 8
    n_groups: int = 1
    ssm_in_mult: float = 1.0
    ssm_out_mult: float = 1.0
    ssm_mults: Tuple[float, ...] = (1.0, 1.0, 1.0, 1.0, 1.0)
    attn_in_mult: float = 1.0
    attn_out_mult: float = 1.0
    key_mult: float = 1.0
    mlp_gate_mult: float = 1.0
    mlp_down_mult: float = 1.0
    lm_head_mult: float = 1.0
    mamba_rms_norm: bool = False
    norm_before_gate: bool = True

    @property
    def conv_dim(self) -> int:
        return self.d_ssm + 2 * self.n_groups * self.d_state


def _mup_vector(args: FalconH1ArchArgs) -> np.ndarray:
    """Per-chunk zxbcdt multipliers over the in_proj output."""
    gts = args.n_groups * args.d_state
    v = np.ones((2 * args.d_ssm + 2 * gts + args.ssd_heads,), np.float32)
    m = args.ssm_mults
    v[: args.d_ssm] *= m[0]
    v[args.d_ssm : 2 * args.d_ssm] *= m[1]
    v[2 * args.d_ssm : 2 * args.d_ssm + gts] *= m[2]
    v[2 * args.d_ssm + gts : 2 * args.d_ssm + 2 * gts] *= m[3]
    v[2 * args.d_ssm + 2 * gts :] *= m[4]
    return v


def _expand_groups(x, n_heads, n_groups):
    b, t, _ = x.shape
    x = x.reshape(b, t, n_groups, -1)
    return jnp.repeat(x, n_heads // n_groups, axis=2)


def _ssm_terms(lp, xc, dt_raw, args):
    bsz, t, _ = xc.shape
    nh, hd, s = args.ssd_heads, args.ssd_head_dim, args.d_state
    x = xc[..., : args.d_ssm].reshape(bsz, t, nh, hd)
    b_mat = _expand_groups(xc[..., args.d_ssm : args.d_ssm + args.n_groups * s],
                           nh, args.n_groups).astype(jnp.float32)
    c_mat = _expand_groups(xc[..., args.d_ssm + args.n_groups * s :],
                           nh, args.n_groups).astype(jnp.float32)
    dt = jax.nn.softplus(dt_raw.astype(jnp.float32)
                         + lp["dt_bias"].astype(jnp.float32))
    a_h = -jnp.exp(lp["a_log"].astype(jnp.float32))
    a = jnp.exp(dt * a_h[None, None, :])[..., None, None]
    b_term = (dt[..., None, None] * b_mat[:, :, :, None, :]
              * x.astype(jnp.float32)[..., None])
    return a, b_term, c_mat, x


def _apply_gate(lp, y, z, args):
    """silu(z) output gate; when ``mamba_rms_norm`` also a grouped RMSNorm,
    applied before or after the gate per ``mamba_norm_before_gate``."""
    z32 = jax.nn.silu(z.astype(jnp.float32))
    if not args.mamba_rms_norm:
        return y * z32
    if not args.norm_before_gate:
        y = y * z32
    b, t, dim = y.shape
    g = args.n_groups
    yg = y.reshape(b, t, g, dim // g)
    var = jnp.mean(jnp.square(yg), axis=-1, keepdims=True)
    yg = yg * jax.lax.rsqrt(var + args.rms_norm_eps)
    yg = lp["gate_norm"].astype(jnp.float32).reshape(g, dim // g) * yg
    y = yg.reshape(b, t, dim)
    if args.norm_before_gate:
        y = y * z32
    return y


def _mixer(lp, hn, args, last_token_idx, conv_state, ssm_state):
    """Falcon-H1 SSD mixer: prefill (last_token_idx given, associative scan) or
    one-token decode."""
    w = args.d_conv
    x_in = hn * args.ssm_in_mult
    proj = (x_in @ lp["in_proj"]) * lp["mup"][None, None, :]
    z = proj[..., : args.d_ssm]
    xbc = proj[..., args.d_ssm : args.d_ssm + args.conv_dim]
    dt_raw = proj[..., args.d_ssm + args.conv_dim :]

    if last_token_idx is not None:
        t = xbc.shape[1]
        idx = last_token_idx[:, None] + 1 - w + jnp.arange(w)[None, :]
        gathered = jnp.take_along_axis(xbc, jnp.clip(idx, 0, t - 1)[:, :, None],
                                       axis=1)
        conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)
        xp = jnp.pad(xbc, ((0, 0), (w - 1, 0), (0, 0)))
        xc = sum(xp[:, j : j + t, :] * lp["conv_w"][j][None, None, :]
                 for j in range(w)) + lp["conv_b"][None, None, :]
        xc = jax.nn.silu(xc)
        a, b_term, c_mat, x = _ssm_terms(lp, xc, dt_raw, args)
        valid = (jnp.arange(t)[None, :]
                 <= last_token_idx[:, None])[..., None, None, None]
        a = jnp.where(valid, a, 1.0)
        b_term = jnp.where(valid, b_term, 0.0)

        def comb(l, r):
            return (r[0] * l[0], r[0] * l[1] + r[1])

        _, h_seq = jax.lax.associative_scan(comb, (a, b_term), axis=1)
        ssm_state = jnp.take_along_axis(
            h_seq, last_token_idx[:, None, None, None, None], axis=1)[:, 0]
        y = jnp.einsum("bthds,bths->bthd", h_seq, c_mat)
        y = y + x.astype(jnp.float32) * lp["d_skip"].astype(
            jnp.float32)[None, None, :, None]
        y = y.reshape(hn.shape[0], t, args.d_ssm)
    else:
        xbc0 = xbc[:, 0]
        conv_state = jnp.concatenate([conv_state[:, 1:], xbc0[:, None, :]],
                                     axis=1)
        xc = jnp.sum(conv_state * lp["conv_w"][None, :, :], axis=1) + lp["conv_b"]
        xc = jax.nn.silu(xc)[:, None, :]
        a, b_term, c_mat, x = _ssm_terms(lp, xc, dt_raw, args)
        ssm_state = a[:, 0] * ssm_state + b_term[:, 0]
        y = jnp.einsum("bhds,bhs->bhd", ssm_state, c_mat[:, 0])
        y = y + x[:, 0].astype(jnp.float32) * lp["d_skip"].astype(
            jnp.float32)[None, :, None]
        y = y.reshape(hn.shape[0], 1, args.d_ssm)

    y = _apply_gate(lp, y, z, args).astype(hn.dtype)
    return y @ lp["out_proj"], conv_state.astype(hn.dtype), ssm_state


def _attn(lp, hn, cos, sin, mask, k_cache, v_cache, positions, bucket, args):
    b, t, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3) * args.key_mult
    v = (hn @ lp["wv"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    q, k = rope_ops.apply_rotary(q, k, cos, sin)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, args.q_size)
    return attn @ lp["wo"], k_cache, v_cache


def _mlp(lp, hn, args):
    y = (hn @ lp["wu"]) * jax.nn.silu((hn @ lp["wg"]) * args.mlp_gate_mult)
    return (y @ lp["wd"]) * args.mlp_down_mult


def _forward(params, args: FalconH1ArchArgs, h, cos, sin, mask, cache,
             positions, bucket, last_token_idx):
    ks, vs, convs, ssms = [], [], [], []
    for li in range(args.num_layers):
        lp = jax.tree.map(lambda p: p[li] if isinstance(p, jnp.ndarray) else p,
                          params["layers"])
        resid = h
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        m_out, conv_state, ssm_state = _mixer(
            lp, hn, args, last_token_idx,
            cache["conv"][li] if positions is not None else None,
            cache["ssm"][li] if positions is not None else None)
        a_out, kc, vc = _attn(lp, hn * args.attn_in_mult, cos, sin, mask,
                              cache["k"][li], cache["v"][li], positions,
                              bucket, args)
        convs.append(conv_state)
        ssms.append(ssm_state)
        ks.append(kc)
        vs.append(vc)
        h = resid + m_out * args.ssm_out_mult + a_out * args.attn_out_mult
        resid = h
        hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
        h = resid + _mlp(lp, hn, args)
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs),
                 "conv": jnp.stack(convs), "ssm": jnp.stack(ssms)}
    return h, out_cache


def prefill_forward(params, args: FalconH1ArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["lm_head"]).astype(jnp.float32) * args.lm_head_mult
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: FalconH1ArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Falcon-H1 decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
    pos_grid = position_ids[:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= pos_grid[:, None, :, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache,
                            position_ids, decode_bucket, None)
    logits = (h @ params["lm_head"]).astype(jnp.float32) * args.lm_head_mult
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class FalconH1InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "mamba_n_heads", "mamba_d_state")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 100000.0), ("rms_norm_eps", 1e-5),
                              ("mamba_d_conv", 4), ("mamba_expand", 2),
                              ("mamba_n_groups", 1), ("mamba_d_ssm", None),
                              ("embedding_multiplier", 1.0),
                              ("ssm_in_multiplier", 1.0),
                              ("ssm_out_multiplier", 1.0),
                              ("ssm_multipliers", [1.0] * 5),
                              ("attention_in_multiplier", 1.0),
                              ("attention_out_multiplier", 1.0),
                              ("key_multiplier", 1.0),
                              ("mlp_multipliers", [1.0, 1.0]),
                              ("lm_head_multiplier", 1.0),
                              ("mamba_rms_norm", False),
                              ("mamba_norm_before_gate", True),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                if default is not None or not hasattr(self, attr):
                    setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if getattr(self, "mamba_d_ssm", None) is None:
            self.mamba_d_ssm = int(self.mamba_expand * self.hidden_size)
        for flag in ("attention_bias", "mamba_proj_bias", "projectors_bias",
                     "mlp_bias"):
            if getattr(self, flag, False):
                raise ValueError(f"Falcon-H1 {flag}=True is not ported: "
                                 "projections here are bias-free (the released "
                                 "Falcon-H1 checkpoints ship without biases)")


class FalconH1ForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config,
                                  "Falcon-H1 (parallel SSM/attention)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return FalconH1InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> FalconH1ArchArgs:
        return FalconH1ArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            embedding_multiplier=float(config.embedding_multiplier),
            tie_word_embeddings=bool(config.tie_word_embeddings),
            d_ssm=int(config.mamba_d_ssm),
            d_state=int(config.mamba_d_state),
            d_conv=int(config.mamba_d_conv),
            ssd_heads=int(config.mamba_n_heads),
            ssd_head_dim=int(config.mamba_d_ssm // config.mamba_n_heads),
            n_groups=int(config.mamba_n_groups),
            ssm_in_mult=float(config.ssm_in_multiplier),
            ssm_out_mult=float(config.ssm_out_multiplier),
            ssm_mults=tuple(float(x) for x in config.ssm_multipliers),
            attn_in_mult=float(config.attention_in_multiplier),
            attn_out_mult=float(config.attention_out_multiplier),
            key_mult=float(config.key_multiplier),
            mlp_gate_mult=float(config.mlp_multipliers[0]),
            mlp_down_mult=float(config.mlp_multipliers[1]),
            lm_head_mult=float(config.lm_head_multiplier),
            mamba_rms_norm=bool(config.mamba_rms_norm),
            norm_before_gate=bool(config.mamba_norm_before_gate),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: FalconH1ArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "k": jnp.zeros((a.num_layers, b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((a.num_layers, b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((a.num_layers, b, a.d_conv, a.conv_dim), dt),
            "ssm": jnp.zeros((a.num_layers, b, a.ssd_heads, a.ssd_head_dim,
                              a.d_state), jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias", "mup"}

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        args = cls.arch_args_from_config(config)
        layers: Dict[str, list] = {k: [] for k in
                                   ("ln1", "ln2", "wq", "wk", "wv", "wo",
                                    "in_proj", "conv_w", "conv_b", "dt_bias",
                                    "a_log", "d_skip", "gate_norm", "out_proj",
                                    "mup", "wg", "wu", "wd")}
        mup = _mup_vector(args)
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            mx = p + "mamba."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "pre_ff_layernorm.weight"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["in_proj"].append(lin_t(mx + "in_proj.weight"))
            layers["conv_w"].append(np.ascontiguousarray(
                get(mx + "conv1d.weight")[:, 0, :].T))
            layers["conv_b"].append(get(mx + "conv1d.bias"))
            layers["dt_bias"].append(get(mx + "dt_bias"))
            layers["a_log"].append(get(mx + "A_log"))
            layers["d_skip"].append(get(mx + "D"))
            if getattr(config, "mamba_rms_norm", False):
                layers["gate_norm"].append(get(mx + "norm.weight"))
            layers["out_proj"].append(lin_t(mx + "out_proj.weight"))
            layers["mup"].append(mup)
            layers["wg"].append(lin_t(p + "feed_forward.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "feed_forward.up_proj.weight"))
            layers["wd"].append(lin_t(p + "feed_forward.down_proj.weight"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items() if v},
            "final_norm": get("model.final_layernorm.weight"),
            "lm_head": lin_t("lm_head.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
