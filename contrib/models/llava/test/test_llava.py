"""llava parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/llava/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow

from contrib.models.llava.test.conftest import tiny_clip_llava  # noqa: F401,E402


def test_llava_clip_vision_encoder_matches_hf(tiny_clip_llava):
    from contrib.models.llava.src.modeling_llava import (
        LlavaForConditionalGeneration)

    hf, cfg = tiny_clip_llava
    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = LlavaForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = LlavaForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(0)
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    feats = app.encode_images(pixels)                   # (2, 4, H_text): CLS dropped
    with torch.no_grad():
        hf_feats = hf.get_image_features(pixel_values=torch.tensor(pixels))
    np.testing.assert_allclose(feats, np.asarray(hf_feats), atol=3e-4, rtol=1e-3)


def test_llava_clip_generate_matches_hf(tiny_clip_llava):
    """LLaVA-1.5 over the image_to_text base: CLIP features land on image-token
    positions, greedy decode matches HF CPU; text-only requests still serve."""
    from contrib.models.llava.src.modeling_llava import (
        LlavaForConditionalGeneration)

    hf, cfg = tiny_clip_llava
    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = LlavaForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = LlavaForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(1)
    ids = rng.integers(1, 250, size=(2, 20))
    ids[:, 2:6] = 255                                   # 4 patches per image
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor(ids),
                             pixel_values=torch.tensor(pixels),
                             max_new_tokens=8, do_sample=False, pad_token_id=0)
    out = app.generate(ids, pixel_values=pixels, max_new_tokens=8)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 20:].numpy())

    # text-only path still serves
    tids = rng.integers(1, 250, size=(2, 10)).astype(np.int64)
    with torch.no_grad():
        hf_t = hf.generate(input_ids=torch.tensor(tids), max_new_tokens=6,
                           do_sample=False, pad_token_id=0)
    out_t = app.generate(tids, max_new_tokens=6)
    np.testing.assert_array_equal(out_t.tokens, hf_t[:, 10:].numpy())
