"""Shared fixture for the llava parity tests (conftest so pytest
resolves it both in direct runs and through the tests/ aggregator)."""

import numpy as np  # noqa: F401
import pytest
import torch  # noqa: F401

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403


@pytest.fixture(scope="module")
def tiny_clip_llava():
    from transformers import (CLIPVisionConfig, LlamaConfig, LlavaConfig,
                              LlavaForConditionalGeneration)

    vc = CLIPVisionConfig(hidden_size=32, intermediate_size=64,
                          num_hidden_layers=3, num_attention_heads=2,
                          image_size=16, patch_size=8, num_channels=3,
                          projection_dim=32)
    tc = LlamaConfig(vocab_size=256, hidden_size=48, intermediate_size=96,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, rope_theta=10000.0,
                     tie_word_embeddings=False)
    cfg = LlavaConfig(vision_config=vc, text_config=tc, image_token_index=255,
                      projector_hidden_act="gelu",
                      vision_feature_layer=-2,
                      vision_feature_select_strategy="default")
    torch.manual_seed(0)
    hf = LlavaForConditionalGeneration(cfg).eval()
    return hf, cfg
