"""LLaVA 1.5 (CLIP tower + Llama) on the TPU framework (contrib port).

≈ reference `contrib/models/llava-v1.5-7b/`. Rides the shared multimodal base
(runtime/image_to_text.py: separate jitted vision encoder, features scattered at
image-token positions of the padded prompt, merged into the CTE embedding —
≈ reference image-to-text pipelined vision→CTE, `models/image_to_text_model_base.py`).
The tower here is CLIP ViT: patch conv + CLS + learned positions, pre-LN,
biased attention/MLP with quick-GELU, features taken at hidden layer
``vision_feature_layer`` (default -2) with the CLS row dropped
("default" select strategy), then the 2-layer GELU projector.
"""

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)
from neuronx_distributed_inference_tpu.ops.vit import ViTSpec, vit_encode
from neuronx_distributed_inference_tpu.runtime.image_to_text import (
    ImageToTextInferenceConfig, TpuModelForImageToText)


def clip_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                       patch_size: int, num_heads: int, eps: float,
                       drop_cls: bool) -> jnp.ndarray:
    """(N, C, H, W) -> (N, T_img, H_text) CLIP ViT features (shared ViT:
    CLS + pre-LN + quick-GELU, no post-norm at feature layer -2) through the
    2-layer GELU projector."""
    spec = ViTSpec(patch_size=patch_size, num_heads=num_heads, eps=eps,
                   act="quick_gelu", patch_bias=False, cls_token=True,
                   pre_ln=True, post_ln=False)
    h = vit_encode(vp, pixel_values, spec)
    if drop_cls:
        h = h[:, 1:]
    feats = jax.nn.gelu(h @ vp["proj_w1"] + vp["proj_b1"], approximate=False)
    return feats @ vp["proj_w2"] + vp["proj_b2"]


class LlavaInferenceConfig(ImageToTextInferenceConfig, LlamaInferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_index")

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        LlamaInferenceConfig.add_derived_config(self)
        for attr, default in (("vision_feature_layer", -2),
                              ("vision_feature_select_strategy", "default")):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        tower = self.vision_config.get("model_type", "clip_vision_model")
        if tower != "clip_vision_model":
            raise ValueError(f"LLaVA port supports CLIP vision towers "
                             f"(got {tower!r}); pixtral towers live in "
                             f"models/pixtral")


def _normalize_keys(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """HF legacy llava layout (`language_model.model.*`, bare `vision_tower.*`)
    -> in-memory layout; in-memory keys pass through."""
    out = {}
    for k, v in state_dict.items():
        if k.startswith("language_model.model."):
            k = "model.language_model." + k[len("language_model.model."):]
        elif k == "language_model.lm_head.weight":
            k = "lm_head.weight"
        elif k.startswith("vision_tower.") or k.startswith("multi_modal_projector."):
            k = "model." + k
        out[k] = v
    return out


class LlavaForConditionalGeneration(TpuModelForImageToText, LlamaForCausalLM):
    """≈ HF LlavaForConditionalGeneration (CLIP tower + llama text model)."""

    @classmethod
    def get_config_cls(cls):
        return LlavaInferenceConfig

    def vision_encode_fn(self):
        vc = self.config.vision_config
        strategy = self.config.vision_feature_select_strategy
        return functools.partial(
            clip_vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            eps=vc.get("layer_norm_eps", 1e-5),
            drop_cls=strategy == "default",
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray], config) -> Dict:
        state_dict = _normalize_keys(state_dict)
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k == "lm_head.weight":
                text_sd[k] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        state_dict = _normalize_keys(state_dict)
        vc = config.vision_config
        # features come from hidden layer `vision_feature_layer` (default -2):
        # only the layers BELOW it run
        n_layers = vc["num_hidden_layers"] + 1 + config.vision_feature_layer \
            if config.vision_feature_layer < 0 else config.vision_feature_layer
        hidden = vc["hidden_size"]

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ("ln1", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
                "ln2", "ln2_b", "w1", "b1", "w2", "b2")
        layers = {k: [] for k in keys}
        for i in range(n_layers):
            p = f"model.vision_tower.vision_model.encoder.layers.{i}."
            layers["ln1"].append(get(p + "layer_norm1.weight"))
            layers["ln1_b"].append(get(p + "layer_norm1.bias"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.out_proj.weight"))
            layers["bo"].append(get(p + "self_attn.out_proj.bias"))
            layers["ln2"].append(get(p + "layer_norm2.weight"))
            layers["ln2_b"].append(get(p + "layer_norm2.bias"))
            layers["w1"].append(lin_t(p + "mlp.fc1.weight"))
            layers["b1"].append(get(p + "mlp.fc1.bias"))
            layers["w2"].append(lin_t(p + "mlp.fc2.weight"))
            layers["b2"].append(get(p + "mlp.fc2.bias"))

        emb = "model.vision_tower.vision_model.embeddings."
        conv = get(emb + "patch_embedding.weight")           # (H_vis, C, p, p)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "cls": get(emb + "class_embedding"),
            "pos_embed": get(emb + "position_embedding.weight"),
            "ln_pre": get("model.vision_tower.vision_model.pre_layrnorm.weight"),
            "ln_pre_b": get("model.vision_tower.vision_model.pre_layrnorm.bias"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "proj_w1": lin_t("model.multi_modal_projector.linear_1.weight"),
            "proj_b1": get("model.multi_modal_projector.linear_1.bias"),
            "proj_w2": lin_t("model.multi_modal_projector.linear_2.weight"),
            "proj_b2": get("model.multi_modal_projector.linear_2.bias"),
        }
