"""StarCoder2 on the TPU framework (contrib port, ≈ reference
`contrib/models/starcoder2-3b/`).

Exercises: rope + biased LayerNorm + biased plain gelu MLP (c_fc/c_proj) + GQA +
sliding window + tied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Starcoder2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0),
                              ("norm_epsilon", 1e-5),
                              ("hidden_act", "gelu_pytorch_tanh"),
                              ("sliding_window", None),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class Starcoder2ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Starcoder2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=h // config.num_attention_heads,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.norm_epsilon,
            activation=config.hidden_act,
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            sliding_window=config.sliding_window,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return rope_ops.default_inv_freq(d, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["bo"].append(get(p + "self_attn.o_proj.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["wg"].append(lin_t(p + "mlp.c_fc.weight"))
            layers["bg"].append(get(p + "mlp.c_fc.bias"))
            layers["wd"].append(lin_t(p + "mlp.c_proj.weight"))
            layers["bd"].append(get(p + "mlp.c_proj.bias"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "final_norm_b": get("model.norm.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
