"""starcoder2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/starcoder2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_starcoder2_parity():
    from transformers import Starcoder2Config, Starcoder2ForCausalLM as HFSc2

    from contrib.models.starcoder2.src.modeling_starcoder2 import (
        Starcoder2ForCausalLM)

    cfg = Starcoder2Config(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           intermediate_size=128, max_position_embeddings=128,
                           hidden_act="gelu_pytorch_tanh", use_bias=True,
                           tie_word_embeddings=True, sliding_window=None,
                           residual_dropout=0.0, embedding_dropout=0.0,
                           attention_dropout=0.0)
    torch.manual_seed(0)
    hf = HFSc2(cfg).eval()
    _run_parity(Starcoder2ForCausalLM, hf, cfg)
