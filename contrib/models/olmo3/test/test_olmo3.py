"""olmo3 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/olmo3/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_olmo3_parity():
    """OLMo 3: the OLMo-2 post-norm block (branch-output norms, full-width
    qk-norm) + a sliding/full layer pattern whose FULL layers use the
    yarn-scaled rope table while sliding layers stay on the unscaled one."""
    from transformers import Olmo3Config, Olmo3ForCausalLM as HFOlmo3

    from contrib.models.olmo3.src.modeling_olmo3 import Olmo3ForCausalLM

    cfg = Olmo3Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, sliding_window=8,
                      layer_types=["sliding_attention", "sliding_attention",
                                   "full_attention", "sliding_attention"],
                      rope_scaling={"rope_type": "yarn", "factor": 4.0,
                                    "original_max_position_embeddings": 32,
                                    "beta_fast": 32.0, "beta_slow": 1.0},
                      max_position_embeddings=128,
                      pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFOlmo3(cfg).eval()
    _run_parity(Olmo3ForCausalLM, hf, cfg, atol=1e-3)
