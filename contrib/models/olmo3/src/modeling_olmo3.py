"""OLMo 3 (AI2) on the TPU framework (contrib port).

≈ reference `contrib/models/OLMo-3-7B-Think/src/modeling_olmo3.py`. OLMo 3
keeps the OLMo-2 block (post-norm: branch outputs RMS-normed before the
residual add, full-width q/k RMSNorm) and adds a 3:1 sliding/full layer
pattern with PER-TYPE rope tables: sliding layers always use the plain
rope_theta table, full-attention layers use the config's scaled table
(e.g. yarn for the long-context "Think" variants). Mapping: the shared
layer-pattern machinery with the main rope table scaled
(`rope_ops.inv_freq_from_hf_config`) and the sliding layers on the
unscaled table via the local-rope hook.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Olmo3InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size", "layer_types")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("rope_scaling", None), ("sliding_window", 4096),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads

    def layer_pattern(self):
        return tuple("sliding" if t == "sliding_attention" else "full"
                     for t in self.layer_types)


class Olmo3ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Olmo3InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            pre_norms=False,
            sandwich_norms=True,
            qk_norm=True,
            qk_norm_scope="full",
            sliding_window=int(config.sliding_window),
            layer_pattern=config.layer_pattern(),
            local_rope_theta=float(config.rope_theta),
            rope_attention_scaling=rope_ops.attention_scaling_from_hf_config(
                getattr(config, "rope_scaling", None)),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # full-attention layers: the (possibly yarn-scaled) table
        return rope_ops.inv_freq_from_hf_config(
            config.head_dim, float(config.rope_theta),
            getattr(config, "rope_scaling", None))

    @classmethod
    def local_inv_freq_from_config(cls, config) -> np.ndarray:
        # sliding layers: always the unscaled rope_theta table
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        H = config.hidden_size
        layers = {k: [] for k in ("ln1", "ln1_post", "wq", "wk", "wv", "wo",
                                  "q_norm", "k_norm",
                                  "ln2", "ln2_post", "wg", "wu", "wd")}
        ones = np.ones((H,), np.float32)
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
            layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
            layers["ln1"].append(ones)
            layers["ln2"].append(ones)
            layers["ln1_post"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_post"].append(get(p + "post_feedforward_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
            "rope_inv_freq_local": cls.local_inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
