"""MPT on the TPU framework (contrib port).

Exercises: ALiBi bias, bias-free LayerNorm + plain gelu MLP, fused Wqkv thirds,
tied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs, alibi_slopes
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class MptInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("d_model", "n_layers", "n_heads", "vocab_size")

    def add_derived_config(self) -> None:
        for attr, default in (("expansion_ratio", 4), ("layer_norm_epsilon", 1e-5)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class MptForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return MptInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.d_model
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.n_layers,
            num_heads=config.n_heads,
            num_kv_heads=config.n_heads,
            head_dim=h // config.n_heads,
            intermediate_size=int(config.expansion_ratio) * h,
            rms_norm_eps=config.layer_norm_epsilon,
            activation="gelu",
            norm_type="layer", norm_bias=False,   # MPT LayerNorms carry no bias
            mlp_kind="plain", mlp_bias=False,
            alibi=True,
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.d_model // config.n_heads
        return np.zeros((d // 2,), np.float32)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.d_model

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "wg", "wd")}
        for i in range(config.n_layers):
            p = f"transformer.blocks.{i}."
            wqkv = get(p + "attn.Wqkv.weight")      # (3H, H), contiguous thirds
            layers["wq"].append(np.ascontiguousarray(wqkv[:h].T))
            layers["wk"].append(np.ascontiguousarray(wqkv[h : 2 * h].T))
            layers["wv"].append(np.ascontiguousarray(wqkv[2 * h :].T))
            layers["wo"].append(
                np.ascontiguousarray(get(p + "attn.out_proj.weight").T))
            layers["ln1"].append(get(p + "norm_1.weight"))
            layers["ln2"].append(get(p + "norm_2.weight"))
            layers["wg"].append(np.ascontiguousarray(get(p + "ffn.up_proj.weight").T))
            layers["wd"].append(
                np.ascontiguousarray(get(p + "ffn.down_proj.weight").T))
        return {
            "embed": get("transformer.wte.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.norm_f.weight"),
            "alibi_slopes": alibi_slopes(config.n_heads),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
