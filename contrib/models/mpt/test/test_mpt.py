"""mpt parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/mpt/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_mpt_parity():
    from transformers import MptConfig, MptForCausalLM as HFMpt

    from contrib.models.mpt.src.modeling_mpt import MptForCausalLM

    cfg = MptConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    expansion_ratio=2, max_seq_len=128)
    torch.manual_seed(0)
    hf = HFMpt(cfg).eval()
    _run_parity(MptForCausalLM, hf, cfg)
