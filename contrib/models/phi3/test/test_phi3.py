"""phi3 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/phi3/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_phi3_parity():
    from transformers import Phi3Config, Phi3ForCausalLM as HFPhi3

    from contrib.models.phi3.src.modeling_phi3 import Phi3ForCausalLM

    cfg = Phi3Config(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, num_key_value_heads=2,
                     intermediate_size=128, max_position_embeddings=128,
                     rope_theta=10000.0, tie_word_embeddings=False,
                     resid_pdrop=0.0, embd_pdrop=0.0, attention_dropout=0.0,
                     sliding_window=None, pad_token_id=0, eos_token_id=2,
                     bos_token_id=1)
    torch.manual_seed(0)
    hf = HFPhi3(cfg).eval()
    _run_parity(Phi3ForCausalLM, hf, cfg)
