"""Phi-3-mini on the TPU framework (contrib port, ≈ reference
`contrib/models/Phi-3-mini-4k-instruct/`).

Llama-shaped (RMSNorm, rope, gated silu MLP) with fused qkv_proj / gate_up_proj
checkpoints split at conversion.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Phi3InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("hidden_act", "silu"), ("rope_scaling", None),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class Phi3ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Phi3InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=h // config.num_attention_heads,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return rope_ops.default_inv_freq(d, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.hidden_size
        d = h // config.num_attention_heads
        q_size = config.num_attention_heads * d
        kv_size = config.num_key_value_heads * d

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            qkv = get(p + "self_attn.qkv_proj.weight")      # (q+2kv, H)
            layers["wq"].append(np.ascontiguousarray(qkv[:q_size].T))
            layers["wk"].append(
                np.ascontiguousarray(qkv[q_size : q_size + kv_size].T))
            layers["wv"].append(
                np.ascontiguousarray(qkv[q_size + kv_size :].T))
            layers["wo"].append(
                np.ascontiguousarray(get(p + "self_attn.o_proj.weight").T))
            gu = get(p + "mlp.gate_up_proj.weight")         # (2I, H)
            inter = config.intermediate_size
            layers["wg"].append(np.ascontiguousarray(gu[:inter].T))
            layers["wu"].append(np.ascontiguousarray(gu[inter:].T))
            layers["wd"].append(
                np.ascontiguousarray(get(p + "mlp.down_proj.weight").T))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return out
