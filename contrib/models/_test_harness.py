"""Shared contrib parity harness (used by every contrib/models/<fam>/test/).

Extracted from the former central tests/test_contrib_models.py: tiny
random-weight config, last-token logit match + multi-step greedy token match
(== the reference contrib checklist, `contrib/models/*/test/`), plus the
hand-rolled torch oracle family for architectures absent from the installed
transformers (internlm3 / orion / minicpm4 — see each family's README).
"""

import math  # noqa: F401

import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)

__all__ = ["_tpu_cfg", "_run_parity", "_run_parity_oracle", "_OracleAttn",
           "_OracleMLP", "_OracleRMSNorm", "_OracleLayer", "_OracleModel"]


def _tpu_cfg():
    return TpuConfig(batch_size=2, seq_len=64, max_context_length=32, dtype="float32",
                     context_encoding_buckets=[16, 32],
                     token_generation_buckets=[32, 64])


def _run_parity(app_cls, hf_model, hf_cfg, atol=5e-4, rtol=1e-3, vocab=256,
                eos_token_id=None):
    config = app_cls.get_config_cls()(
        _tpu_cfg(), load_config=load_pretrained_config(hf_cfg.to_dict()))
    app = app_cls(None, config)
    state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = app.convert_hf_state_dict(state, app.config)
    app._put_params(params)

    rng = np.random.default_rng(0)
    input_ids = rng.integers(1, vocab, size=(2, 12)).astype(np.int64)
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(input_ids)).logits[:, -1].numpy()
    out = app.generate(input_ids, max_new_tokens=1, return_logits=True)
    np.testing.assert_allclose(out.logits[0], hf_logits, atol=atol, rtol=rtol)

    with torch.no_grad():
        hf_out = hf_model.generate(torch.tensor(input_ids), max_new_tokens=10,
                                   do_sample=False, pad_token_id=0)
    out = app.generate(input_ids, max_new_tokens=10, eos_token_id=eos_token_id)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 12:].numpy())


class _OracleAttn(torch.nn.Module):
    def __init__(self, H, nq, nkv, d, qkv_bias, o_bias):
        super().__init__()
        self.q_proj = torch.nn.Linear(H, nq * d, bias=qkv_bias)
        self.k_proj = torch.nn.Linear(H, nkv * d, bias=qkv_bias)
        self.v_proj = torch.nn.Linear(H, nkv * d, bias=qkv_bias)
        self.o_proj = torch.nn.Linear(nq * d, H, bias=o_bias)
        self.nq, self.nkv, self.d = nq, nkv, d

    def forward(self, x, inv_freq, attn_scale):
        B, S, _ = x.shape
        q = self.q_proj(x).view(B, S, self.nq, self.d).transpose(1, 2)
        k = self.k_proj(x).view(B, S, self.nkv, self.d).transpose(1, 2)
        v = self.v_proj(x).view(B, S, self.nkv, self.d).transpose(1, 2)
        pos = torch.arange(S, dtype=torch.float32)
        freqs = torch.outer(pos, torch.tensor(inv_freq))
        emb = torch.cat([freqs, freqs], dim=-1)
        cos = (emb.cos() * attn_scale)[None, None]
        sin = (emb.sin() * attn_scale)[None, None]

        def rot(t):
            h = t.shape[-1] // 2
            return torch.cat([-t[..., h:], t[..., :h]], dim=-1)

        q = q * cos + rot(q) * sin
        k = k * cos + rot(k) * sin
        rep = self.nq // self.nkv
        k = k.repeat_interleave(rep, dim=1)
        v = v.repeat_interleave(rep, dim=1)
        scores = (q @ k.transpose(-1, -2)) / math.sqrt(self.d)
        mask = torch.full((S, S), float("-inf")).triu(1)
        attn = torch.softmax(scores + mask, dim=-1) @ v
        return self.o_proj(attn.transpose(1, 2).reshape(B, S, -1))


class _OracleMLP(torch.nn.Module):
    def __init__(self, H, I, bias):
        super().__init__()
        self.gate_proj = torch.nn.Linear(H, I, bias=bias)
        self.up_proj = torch.nn.Linear(H, I, bias=bias)
        self.down_proj = torch.nn.Linear(I, H, bias=bias)

    def forward(self, x):
        return self.down_proj(torch.nn.functional.silu(self.gate_proj(x))
                              * self.up_proj(x))


class _OracleRMSNorm(torch.nn.Module):
    def __init__(self, H, eps):
        super().__init__()
        self.weight = torch.nn.Parameter(torch.ones(H))
        self.eps = eps

    def forward(self, x):
        var = x.pow(2).mean(-1, keepdim=True)
        return self.weight * x * torch.rsqrt(var + self.eps)


class _OracleLayer(torch.nn.Module):
    def __init__(self, H, I, nq, nkv, d, eps, norm, qkv_bias, proj_bias):
        super().__init__()
        mk = ((lambda: torch.nn.LayerNorm(H, eps=eps)) if norm == "layer"
              else (lambda: _OracleRMSNorm(H, eps)))
        self.input_layernorm = mk()
        self.post_attention_layernorm = mk()
        self.self_attn = _OracleAttn(H, nq, nkv, d, qkv_bias, proj_bias)
        self.mlp = _OracleMLP(H, I, proj_bias)


class _OracleModel(torch.nn.Module):
    """Pre-norm llama-variant oracle: norm in {rms, layer}; optional qkv/proj
    biases; muP knobs (scale_emb, per-branch residual multiplier, final
    hidden divided by hidden/dim_model_base)."""

    def __init__(self, V, H, I, L, nq, nkv, d, eps=1e-5, norm="rms",
                 qkv_bias=False, proj_bias=False, inv_freq=None,
                 attn_scale=1.0, scale_emb=1.0, res_mult=1.0,
                 logits_div=1.0):
        super().__init__()
        inner = torch.nn.Module()
        inner.embed_tokens = torch.nn.Embedding(V, H)
        inner.layers = torch.nn.ModuleList(
            [_OracleLayer(H, I, nq, nkv, d, eps, norm, qkv_bias, proj_bias)
             for _ in range(L)])
        inner.norm = (torch.nn.LayerNorm(H, eps=eps) if norm == "layer"
                      else _OracleRMSNorm(H, eps))
        self.model = inner
        self.lm_head = torch.nn.Linear(H, V, bias=False)
        self.inv_freq = (inv_freq if inv_freq is not None
                         else (10000.0 ** (-np.arange(0, d, 2) / d)).astype(np.float32))
        self.attn_scale = attn_scale
        self.scale_emb, self.res_mult, self.logits_div = scale_emb, res_mult, logits_div

    def forward(self, ids):
        h = self.model.embed_tokens(ids) * self.scale_emb
        for lyr in self.model.layers:
            h = h + lyr.self_attn(lyr.input_layernorm(h), self.inv_freq,
                                  self.attn_scale) * self.res_mult
            h = h + lyr.mlp(lyr.post_attention_layernorm(h)) * self.res_mult
        h = self.model.norm(h) / self.logits_div
        return self.lm_head(h)


def _run_parity_oracle(app_cls, oracle, hf_cfg_dict, atol=5e-4, rtol=1e-3):
    config = app_cls.get_config_cls()(
        _tpu_cfg(), load_config=load_pretrained_config(hf_cfg_dict))
    app = app_cls(None, config)
    state = {k: v.detach().numpy() for k, v in oracle.state_dict().items()}
    params = app.convert_hf_state_dict(state, app.config)
    app._put_params(params)

    rng = np.random.default_rng(0)
    ids = rng.integers(1, hf_cfg_dict["vocab_size"], size=(2, 12)).astype(np.int64)
    with torch.no_grad():
        ref_logits = oracle(torch.tensor(ids))[:, -1].numpy()
    out = app.generate(ids, max_new_tokens=1, return_logits=True)
    np.testing.assert_allclose(out.logits[0], ref_logits, atol=atol, rtol=rtol)

    cur = torch.tensor(ids)
    for _ in range(8):                      # full-recompute greedy oracle
        with torch.no_grad():
            nxt = oracle(cur)[:, -1].argmax(-1)
        cur = torch.cat([cur, nxt[:, None]], 1)
    out = app.generate(ids, max_new_tokens=8, eos_token_id=-1)
    np.testing.assert_array_equal(out.tokens, cur[:, 12:].numpy())
