"""ernie4_5 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/ernie4_5/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_ernie4_5_parity():
    from transformers import Ernie4_5Config
    from transformers import Ernie4_5ForCausalLM as HFErnie

    from contrib.models.ernie4_5.src.modeling_ernie4_5 import Ernie45ForCausalLM

    cfg = Ernie4_5Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, head_dim=16, use_bias=False,
                         pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFErnie(cfg).eval()
    _run_parity(Ernie45ForCausalLM, hf, cfg)
