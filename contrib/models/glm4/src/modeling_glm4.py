"""GLM-4-0414 (glm4 architecture) on the TPU framework (contrib port).

≈ reference contrib GLM-4 family. Identical to glm (half-width
interleaved-pair partial rotary, QKV biases, fused gate_up MLP) plus
gemma2-style sandwich norms: `post_self_attn_layernorm` scales the attention
branch output and `post_mlp_layernorm` the MLP branch output before each
residual add (HF `Glm4DecoderLayer.forward`), riding the base
``sandwich_norms`` machinery.
"""

from typing import Dict

import numpy as np

from contrib.models.glm.src.modeling_glm import (GlmForCausalLM,
                                                 GlmInferenceConfig)
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs


class Glm4InferenceConfig(GlmInferenceConfig):
    pass


class Glm4ForCausalLM(GlmForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Glm4InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        import dataclasses
        return dataclasses.replace(super().arch_args_from_config(config),
                                   sandwich_norms=True)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        out = super().convert_hf_state_dict(state_dict, config)
        post1, post2 = [], []
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            post1.append(np.asarray(
                state_dict[p + "post_self_attn_layernorm.weight"]))
            post2.append(np.asarray(
                state_dict[p + "post_mlp_layernorm.weight"]))
        out["layers"]["ln1_post"] = np.stack(post1)
        out["layers"]["ln2_post"] = np.stack(post2)
        return out
