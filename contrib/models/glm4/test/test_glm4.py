"""glm4 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/glm4/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_glm4_parity():
    """GLM-4-0414: glm plus sandwich norms (post_self_attn / post_mlp branch
    norms before each residual add)."""
    from transformers import Glm4Config, Glm4ForCausalLM as HFGlm4

    from contrib.models.glm4.src.modeling_glm4 import Glm4ForCausalLM

    cfg = Glm4Config(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, num_key_value_heads=2,
                     intermediate_size=128, partial_rotary_factor=0.5,
                     head_dim=16, attention_bias=True, rope_theta=10000.0,
                     tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(0)
    hf = HFGlm4(cfg).eval()
    _run_parity(Glm4ForCausalLM, hf, cfg)
