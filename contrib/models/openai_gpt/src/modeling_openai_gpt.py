"""OpenAI GPT (GPT-1) on the TPU framework (contrib port).

≈ reference contrib gpt lineage. The one TRUE post-LN decoder in the hub:
LayerNorm is applied to the residual SUM (`Block.forward`: n = ln_1(x + attn),
h = ln_2(n + mlp)), which the shared core's branch-norm modes (olmo2/exaone4
style) cannot express — so this family carries a compact custom forward.
Learned positions, fused Conv1D c_attn (no transpose), tanh-gelu MLP (HF's
ACT_FNS maps afn="gelu" to gelu_new), no final norm, tied head.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import layer_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


def _attn(lp, h, mask, k_cache, v_cache, positions, bucket, args):
    b, t, hd = h.shape
    qkv = h @ lp["c_attn"] + lp["c_attn_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, args.num_heads, args.head_dim).transpose(0, 2, 1, 3)
    k = k.reshape(b, t, args.num_heads, args.head_dim).transpose(0, 2, 1, 3)
    v = v.reshape(b, t, args.num_heads, args.head_dim).transpose(0, 2, 1, 3)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, hd)
    return attn @ lp["c_proj"] + lp["c_proj_b"], k_cache, v_cache


def _forward(params, args, h, mask, cache, positions, bucket):
    eps = args.rms_norm_eps
    ks, vs = [], []
    for li in range(args.num_layers):
        lp = jax.tree.map(lambda p: p[li], params["layers"])
        a, kc, vc = _attn(lp, h, mask, cache["k"][li], cache["v"][li],
                          positions, bucket, args)
        ks.append(kc)
        vs.append(vc)
        n = layer_norm(h + a, lp["ln1"], lp["ln1_b"], eps)  # post-LN on SUM
        m = (jax.nn.gelu(n @ lp["c_fc"] + lp["c_fc_b"], approximate=True)
             @ lp["c_mlp_proj"]) + lp["c_mlp_proj_b"]
        h = layer_norm(n + m, lp["ln2"], lp["ln2_b"], eps)
    return h, {"k": jnp.stack(ks), "v": jnp.stack(vs)}


def prefill_forward(params, args, input_ids, position_ids, last_token_idx,
                    cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = (jnp.take(params["embed"], input_ids, axis=0)
         + jnp.take(params["pos_embed"], position_ids, axis=0))
    t = input_ids.shape[1]
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, mask, cache, None, None)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["embed"].T).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args, input_ids, position_ids, cache, decode_bucket,
                   mesh=None, rules=None, adapter_ids=None, tree=None,
                   return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("GPT-1 decode is single-token only")
    h = (jnp.take(params["embed"], input_ids, axis=0)
         + jnp.take(params["pos_embed"], position_ids[:, None], axis=0))
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= position_ids[:, None, None, None]
    h, out_cache = _forward(params, args, h, mask, cache, position_ids,
                            decode_bucket)
    logits = (h @ params["embed"].T).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class OpenAIGPTInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("n_embd", "n_layer", "n_head", "vocab_size",
                           "n_positions")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5), ("afn", "gelu")):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if self.afn != "gelu":
            raise ValueError(f"GPT-1 activation {self.afn!r} is not ported")


class OpenAIGPTForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "GPT-1 (post-LN)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return OpenAIGPTInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.n_embd
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.n_layer,
            num_heads=config.n_head,
            num_kv_heads=config.n_head,
            head_dim=h // config.n_head,
            intermediate_size=4 * h,
            rms_norm_eps=config.layer_norm_epsilon,
            learned_pos=True,
            tie_word_embeddings=True,
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return np.zeros(((config.n_embd // config.n_head) // 2,), np.float32)

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "k": jnp.zeros((a.num_layers, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((a.num_layers, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        self.params = jax.tree.map(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("c_attn", "c_attn_b", "c_proj", "c_proj_b",
                                  "ln1", "ln1_b", "c_fc", "c_fc_b",
                                  "c_mlp_proj", "c_mlp_proj_b", "ln2", "ln2_b")}
        for i in range(config.n_layer):
            p = f"transformer.h.{i}."
            # HF Conv1D stores (in, out): no transpose needed
            layers["c_attn"].append(get(p + "attn.c_attn.weight"))
            layers["c_attn_b"].append(get(p + "attn.c_attn.bias"))
            layers["c_proj"].append(get(p + "attn.c_proj.weight"))
            layers["c_proj_b"].append(get(p + "attn.c_proj.bias"))
            layers["ln1"].append(get(p + "ln_1.weight"))
            layers["ln1_b"].append(get(p + "ln_1.bias"))
            layers["c_fc"].append(get(p + "mlp.c_fc.weight"))
            layers["c_fc_b"].append(get(p + "mlp.c_fc.bias"))
            layers["c_mlp_proj"].append(get(p + "mlp.c_proj.weight"))
            layers["c_mlp_proj_b"].append(get(p + "mlp.c_proj.bias"))
            layers["ln2"].append(get(p + "ln_2.weight"))
            layers["ln2_b"].append(get(p + "ln_2.bias"))
        return {
            "embed": get("transformer.tokens_embed.weight"),
            "pos_embed": get("transformer.positions_embed.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
