"""openai_gpt parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/openai_gpt/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_openai_gpt_parity():
    """GPT-1: true post-LN (LayerNorm on the residual SUM), learned positions,
    no final norm — the custom-forward post-LN representative."""
    from transformers import OpenAIGPTConfig, OpenAIGPTLMHeadModel

    from contrib.models.openai_gpt.src.modeling_openai_gpt import (
        OpenAIGPTForCausalLM)

    cfg = OpenAIGPTConfig(vocab_size=256, n_positions=128, n_embd=64,
                          n_layer=2, n_head=4, afn="gelu",
                          resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = OpenAIGPTLMHeadModel(cfg).eval()
    _run_parity(OpenAIGPTForCausalLM, hf, cfg)
