"""gemma parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gemma/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_gemma_parity():
    from transformers import GemmaConfig, GemmaForCausalLM as HFGemma

    from contrib.models.gemma.src.modeling_gemma import GemmaForCausalLM

    cfg = GemmaConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      intermediate_size=128, head_dim=16,
                      hidden_activation="gelu_pytorch_tanh",
                      max_position_embeddings=128)
    torch.manual_seed(0)
    hf = HFGemma(cfg).eval()
    # gemma's default eos (token 1) can be emitted by the random model; thread it
    # so both sides stop identically
    _run_parity(GemmaForCausalLM, hf, cfg, eos_token_id=1)
