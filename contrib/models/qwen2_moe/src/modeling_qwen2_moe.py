"""Qwen2-MoE (Qwen1.5-MoE-A2.7B architecture) on the TPU framework (contrib port).

Qwen2 attention (biased qkv) + fine-grained MoE with a sigmoid-gated SHARED
expert running densely beside the routed experts (softmax-topk routing without
renormalization) — maps onto ops/moe.py's shared-expert machinery.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Qwen2MoeInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "num_experts", "num_experts_per_tok",
                           "moe_intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("norm_topk_prob", False),
                              ("shared_expert_intermediate_size", 0),
                              ("decoder_sparse_step", 1),
                              ("mlp_only_layers", [])):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if self.decoder_sparse_step != 1 or self.mlp_only_layers:
            raise ValueError("mixed dense/sparse Qwen2-MoE layers are not "
                             "ported yet (decoder_sparse_step must be 1)")


class Qwen2MoeForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Qwen2MoeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.moe_intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_bias=True,
            moe=MoEArgs(num_experts=config.num_experts,
                        experts_per_tok=config.num_experts_per_tok,
                        norm_topk_prob=bool(config.norm_topk_prob),
                        shared_expert_intermediate_size=int(
                            config.shared_expert_intermediate_size),
                        shared_expert_gated=True),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        E = config.num_experts
        layers = {k: [] for k in
                  ("ln1", "wq", "wk", "wv", "bq", "bk", "bv", "wo", "ln2",
                   "router", "wg", "wu", "wd",
                   "shared_wg", "shared_wu", "shared_wd", "shared_gate")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            m = p + "mlp."
            layers["router"].append(lin_t(m + "gate.weight"))
            layers["wg"].append(np.stack(
                [lin_t(m + f"experts.{e}.gate_proj.weight") for e in range(E)]))
            layers["wu"].append(np.stack(
                [lin_t(m + f"experts.{e}.up_proj.weight") for e in range(E)]))
            layers["wd"].append(np.stack(
                [lin_t(m + f"experts.{e}.down_proj.weight") for e in range(E)]))
            layers["shared_wg"].append(lin_t(m + "shared_expert.gate_proj.weight"))
            layers["shared_wu"].append(lin_t(m + "shared_expert.up_proj.weight"))
            layers["shared_wd"].append(lin_t(m + "shared_expert.down_proj.weight"))
            layers["shared_gate"].append(lin_t(m + "shared_expert_gate.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
