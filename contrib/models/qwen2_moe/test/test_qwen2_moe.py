"""qwen2_moe parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/qwen2_moe/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_qwen2_moe_parity():
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM as HFQwen2Moe

    from contrib.models.qwen2_moe.src.modeling_qwen2_moe import (
        Qwen2MoeForCausalLM)

    cfg = Qwen2MoeConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                         moe_intermediate_size=48,
                         shared_expert_intermediate_size=96,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, num_experts=4,
                         num_experts_per_tok=2, norm_topk_prob=False,
                         decoder_sparse_step=1, mlp_only_layers=[],
                         sliding_window=None, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFQwen2Moe(cfg).eval()
    _run_parity(Qwen2MoeForCausalLM, hf, cfg, atol=1e-3, rtol=1e-3)
