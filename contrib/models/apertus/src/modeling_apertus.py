"""Apertus (Swiss AI) on the TPU framework (contrib port).

Llama geometry with the Apertus specifics: ungated MLP through the xIELU
activation (LEARNED per-layer alpha_p/alpha_n — the hub's first
learned-parameter activation), per-head q/k RMSNorm, very high rope theta.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class ApertusInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 12000000.0), ("rms_norm_eps", 1e-5),
                              ("attention_bias", False), ("mlp_bias", False),
                              ("hidden_act", "xielu"),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class ApertusForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return ApertusInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation="xielu",
            mlp_kind="plain",
            mlp_bias=bool(config.mlp_bias),
            attention_bias=bool(config.attention_bias),
            o_bias=bool(config.attention_bias),
            qk_norm=True,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo",
                                  "q_norm", "k_norm",
                                  "ln2", "wg", "wd", "xielu_ap", "xielu_an")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
            layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
            layers["ln1"].append(get(p + "attention_layernorm.weight"))
            layers["ln2"].append(get(p + "feedforward_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
            layers["xielu_ap"].append(
                get(p + "mlp.act_fn.alpha_p").astype(np.float32).reshape(1))
            layers["xielu_an"].append(
                get(p + "mlp.act_fn.alpha_n").astype(np.float32).reshape(1))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
