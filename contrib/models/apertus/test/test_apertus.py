"""apertus parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/apertus/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_apertus_parity():
    """Apertus: learned-parameter xIELU activation (per-layer alpha_p/alpha_n)
    + per-head qk-norm — the hub's first learned activation."""
    from transformers import ApertusConfig, ApertusForCausalLM as HFApertus

    from contrib.models.apertus.src.modeling_apertus import ApertusForCausalLM

    cfg = ApertusConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, hidden_act="xielu",
                        pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    # the xIELU module keeps its alpha params in bf16; float() them for numpy
    hf = HFApertus(cfg).eval().float()
    _run_parity(ApertusForCausalLM, hf, cfg, atol=1e-3, rtol=1e-3)
