"""GPT-2 on the TPU framework (contrib port).

≈ reference `contrib/models/gpt2/src/` port pattern: thin arch description +
HF-state-dict converter over the shared functional core. GPT-2 exercises the
contrib-arch primitives: learned position embeddings (no rope), biased LayerNorm,
fused c_attn QKV split, plain (non-gated) gelu MLP, tied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class GPT2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("n_embd", "n_layer", "n_head", "vocab_size", "n_positions")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5),
                              ("activation_function", "gelu_new"),
                              ("n_inner", None)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if self.n_inner is None:
            self.n_inner = 4 * self.n_embd


class GPT2ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GPT2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.n_embd
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.n_layer,
            num_heads=config.n_head,
            num_kv_heads=config.n_head,
            head_dim=h // config.n_head,
            intermediate_size=config.n_inner,
            rms_norm_eps=config.layer_norm_epsilon,
            activation=config.activation_function,
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            learned_pos=True,
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # learned positions: rope collapses to identity via a zero frequency table
        return np.zeros(((config.n_embd // config.n_head) // 2,), np.float32)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.n_embd

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        for i in range(config.n_layer):
            p = f"transformer.h.{i}."
            # HF Conv1D stores weights (in, out): no transpose needed
            c_attn = get(p + "attn.c_attn.weight")          # (H, 3H)
            c_attn_b = get(p + "attn.c_attn.bias")          # (3H,)
            layers["wq"].append(c_attn[:, :h])
            layers["wk"].append(c_attn[:, h : 2 * h])
            layers["wv"].append(c_attn[:, 2 * h :])
            layers["bq"].append(c_attn_b[:h])
            layers["bk"].append(c_attn_b[h : 2 * h])
            layers["bv"].append(c_attn_b[2 * h :])
            layers["wo"].append(get(p + "attn.c_proj.weight"))
            layers["bo"].append(get(p + "attn.c_proj.bias"))
            layers["ln1"].append(get(p + "ln_1.weight"))
            layers["ln1_b"].append(get(p + "ln_1.bias"))
            layers["ln2"].append(get(p + "ln_2.weight"))
            layers["ln2_b"].append(get(p + "ln_2.bias"))
            layers["wg"].append(get(p + "mlp.c_fc.weight"))
            layers["bg"].append(get(p + "mlp.c_fc.bias"))
            layers["wd"].append(get(p + "mlp.c_proj.weight"))
            layers["bd"].append(get(p + "mlp.c_proj.bias"))
        return {
            "embed": get("transformer.wte.weight"),
            "pos_embed": get("transformer.wpe.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
