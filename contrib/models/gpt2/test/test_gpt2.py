"""gpt2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gpt2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_gpt2_parity():
    from transformers import GPT2Config, GPT2LMHeadModel

    from contrib.models.gpt2.src.modeling_gpt2 import GPT2ForCausalLM

    cfg = GPT2Config(vocab_size=256, n_positions=128, n_embd=64, n_layer=2,
                     n_head=4, activation_function="gelu_new",
                     resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(cfg).eval()
    _run_parity(GPT2ForCausalLM, hf, cfg)
