"""internlm3 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/internlm3/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_internlm3_parity():
    """InternLM3: llama geometry + independent qkv_bias (q/k/v) and bias
    (o_proj + gated-MLP) knobs, both exercised."""
    from contrib.models.internlm3.src.modeling_internlm3 import (
        InternLM3ForCausalLM)

    cfg = dict(model_type="internlm3", vocab_size=256, hidden_size=64,
               intermediate_size=128, num_hidden_layers=2,
               num_attention_heads=4, num_key_value_heads=2, head_dim=16,
               qkv_bias=True, bias=True, rms_norm_eps=1e-5,
               rope_theta=10000.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    oracle = _OracleModel(256, 64, 128, 2, 4, 2, 16, eps=1e-5,
                          qkv_bias=True, proj_bias=True).eval()
    with torch.no_grad():                    # biases are zero-init; randomize
        for n, p in oracle.named_parameters():
            if n.endswith(".bias"):
                p.copy_(torch.randn_like(p) * 0.05)
    _run_parity_oracle(InternLM3ForCausalLM, oracle, cfg)
