"""InternLM3 (Shanghai AI Lab) on the TPU framework (contrib port).

≈ reference `contrib/models/internlm3-8b-instruct/src/modeling_internlm3.py`.
Llama-geometry GQA decoder with two independent bias knobs: ``qkv_bias``
(biases on q/k/v only) and ``bias`` (biases on o_proj and the gated MLP),
RMSNorm, silu-gated MLP, optional dynamic/linear rope scaling.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class InternLM3InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("qkv_bias", False), ("bias", False),
                              ("rope_scaling", None),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class InternLM3ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return InternLM3InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_bias=bool(config.qkv_bias),
            o_bias=bool(config.bias),
            mlp_bias=bool(config.bias),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.inv_freq_from_hf_config(
            config.head_dim, float(config.rope_theta),
            getattr(config, "rope_scaling", None))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ["ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd"]
        if config.qkv_bias:
            keys += ["bq", "bk", "bv"]
        if config.bias:
            keys += ["bo", "bg", "bu", "bd"]
        layers = {k: [] for k in keys}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            if config.qkv_bias:
                layers["bq"].append(get(p + "self_attn.q_proj.bias"))
                layers["bk"].append(get(p + "self_attn.k_proj.bias"))
                layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            if config.bias:
                layers["bo"].append(get(p + "self_attn.o_proj.bias"))
                layers["bg"].append(get(p + "mlp.gate_proj.bias"))
                layers["bu"].append(get(p + "mlp.up_proj.bias"))
                layers["bd"].append(get(p + "mlp.down_proj.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
