"""Falcon-7B on the TPU framework (contrib port, ≈ reference
`contrib/models/falcon-7b/`).

Exercises: multi-query attention (1 KV head), parallel residual with a shared
LayerNorm, fused MQA query_key_value split, bias-free plain gelu MLP, tied head.
(The 40B/180B new_decoder_architecture variant is not covered.)
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class FalconInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0),
                              ("layer_norm_epsilon", 1e-5),
                              ("parallel_attn", True),
                              ("multi_query", True),
                              ("bias", False),
                              ("new_decoder_architecture", False),
                              ("alibi", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if self.new_decoder_architecture:
            raise NotImplementedError("falcon new_decoder_architecture (40B/180B) "
                                      "is not supported")
        if self.alibi:
            raise NotImplementedError("alibi falcon variants are not supported")


class FalconForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return FalconInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=1 if config.multi_query else config.num_attention_heads,
            head_dim=h // config.num_attention_heads,
            intermediate_size=4 * h,
            rms_norm_eps=config.layer_norm_epsilon,
            activation="gelu",
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=bool(config.bias),
            attention_bias=bool(config.bias), o_bias=bool(config.bias),
            parallel_residual=bool(config.parallel_attn), shared_ln=True,
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return rope_ops.default_inv_freq(d, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.hidden_size
        nh = config.num_attention_heads
        d = h // nh

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "wo",
                                  "ln2", "ln2_b", "wg", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"transformer.h.{i}."
            # fused MQA: rows [q (nh*d), k (d), v (d)]
            qkv = get(p + "self_attention.query_key_value.weight")
            layers["wq"].append(np.ascontiguousarray(qkv[: nh * d].T))
            layers["wk"].append(np.ascontiguousarray(qkv[nh * d : nh * d + d].T))
            layers["wv"].append(np.ascontiguousarray(qkv[nh * d + d :].T))
            layers["wo"].append(
                np.ascontiguousarray(get(p + "self_attention.dense.weight").T))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(np.ones_like(get(p + "input_layernorm.weight")))
            layers["ln2_b"].append(np.zeros_like(get(p + "input_layernorm.bias")))
            layers["wg"].append(
                np.ascontiguousarray(get(p + "mlp.dense_h_to_4h.weight").T))
            layers["wd"].append(
                np.ascontiguousarray(get(p + "mlp.dense_4h_to_h.weight").T))
        return {
            "embed": get("transformer.word_embeddings.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
