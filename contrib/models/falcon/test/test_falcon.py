"""falcon parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/falcon/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_falcon_parity():
    from transformers import FalconConfig, FalconForCausalLM as HFFalcon

    from contrib.models.falcon.src.modeling_falcon import FalconForCausalLM

    cfg = FalconConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4, multi_query=True,
                       parallel_attn=True, bias=False,
                       new_decoder_architecture=False, alibi=False,
                       rope_theta=10000.0, max_position_embeddings=128,
                       hidden_dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf = HFFalcon(cfg).eval()
    _run_parity(FalconForCausalLM, hf, cfg)
