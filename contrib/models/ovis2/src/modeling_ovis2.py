"""Ovis2 (AIDC visual-tokenizer multimodal; represents Ovis2.5) on the TPU
framework (contrib port).

≈ reference `contrib/models/Ovis2.5-9B/`. Ovis is architecturally unlike the
projector VLMs: the AIMv2-style tower (RMSNorm blocks, silu-gated MLP,
bias-free attention, patch-embed RMSNorm before learned positions) feeds a
2x2 hidden-stride merge, then a linear+LayerNorm head produces a SOFTMAX
distribution over a discrete *visual vocabulary*; image features are that
probability vector times a learned visual embedding table (vte) — soft visual
tokens in text-embedding space. The last ``num_visual_indicator_tokens`` vte
rows are bound to the special indicator token ids (img_start/end etc.), whose
text embeddings are REPLACED by their vte rows at prefill; served here by
extending the shared base's feature scatter. Text backbone: qwen2.
"""

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.models.qwen2.modeling_qwen2 import (
    Qwen2ForCausalLM, Qwen2InferenceConfig)
from neuronx_distributed_inference_tpu.ops.norms import layer_norm
from neuronx_distributed_inference_tpu.ops.vit import ViTSpec, vit_encode
from neuronx_distributed_inference_tpu.runtime.image_to_text import (
    ImageToTextInferenceConfig, TpuModelForImageToText)


def ovis2_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                        patch_size: int, num_heads: int, eps: float,
                        ln_eps: float, hidden_stride: int,
                        qkv_bias: bool) -> jnp.ndarray:
    """(N, C, H, W) -> (N, T_img, H_text) soft visual tokens through the vte."""
    n = pixel_values.shape[0]
    gh = pixel_values.shape[2] // patch_size
    gw = pixel_values.shape[3] // patch_size
    spec = ViTSpec(patch_size=patch_size, num_heads=num_heads, eps=eps,
                   norm="rms", mlp="gated_silu", attn_bias=qkv_bias,
                   embed_rms=True)
    h = vit_encode(vp, pixel_values, spec)

    # 2x2 (hidden_stride) spatial merge: (gh/hs * gw/hs, hs^2 * d_vis)
    hs = hidden_stride
    hv = h.shape[-1]
    grid = h.reshape(n, gh, gw, hv)
    grid = grid.reshape(n, gh // hs, hs, gw // hs, hs, hv)
    merged = grid.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, (gh // hs) * (gw // hs), hs * hs * hv)

    logits = merged @ vp["head_w"]
    logits = layer_norm(logits, vp["head_norm"], vp["head_norm_b"], eps=ln_eps)
    probs = jax.nn.softmax(logits, axis=-1)       # (N, T, V_vis - n_indicator)
    # zero-padded indicator probabilities contribute nothing: use the vte slice
    return probs @ vp["vte"]                      # (N, T, H_text)


class Ovis2InferenceConfig(ImageToTextInferenceConfig, Qwen2InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config",)

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        Qwen2InferenceConfig.add_derived_config(self)
        if not hasattr(self, "image_token_index"):
            self.image_token_index = getattr(self, "image_token_id", None)
        if self.image_token_index is None:
            raise ValueError("ovis2 config needs image_token_id")
        if not hasattr(self, "visual_indicator_token_ids"):
            self.visual_indicator_token_ids = []


class Ovis2ForConditionalGeneration(TpuModelForImageToText, Qwen2ForCausalLM):
    """≈ HF Ovis2ForConditionalGeneration."""

    def __init__(self, model_path, config, mesh=None):
        super().__init__(model_path, config, mesh=mesh)
        self._indicator_feats = None    # (n_indicator, H_text), host

    @classmethod
    def get_config_cls(cls):
        return Ovis2InferenceConfig

    def vision_encode_fn(self):
        vc = self.config.vision_config
        return functools.partial(
            ovis2_vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            eps=vc.get("rms_norm_eps", 1e-5),
            ln_eps=1e-5,
            hidden_stride=int(vc.get("hidden_stride", 1)),
            qkv_bias=bool(vc.get("qkv_bias", True)),
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k == "lm_head.weight":
                text_sd[k] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        def norm_key(k):
            return k[6:] if k.startswith("model.") else k

        state_dict = {norm_key(k): v for k, v in state_dict.items()}
        vc = config.vision_config
        hidden = vc["hidden_size"]
        qkv_bias = bool(vc.get("qkv_bias", True))
        n_ind = int(vc.get("num_visual_indicator_tokens", 0))

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ["ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd"]
        if qkv_bias:
            keys += ["bq", "bk", "bv", "bo"]
        layers = {k: [] for k in keys}
        for i in range(vc["num_hidden_layers"]):
            p = f"vision_tower.transformer.encoder.layers.{i}."
            layers["ln1"].append(get(p + "rms_norm1.weight"))
            layers["wq"].append(lin_t(p + "attention.q_proj.weight"))
            layers["wk"].append(lin_t(p + "attention.k_proj.weight"))
            layers["wv"].append(lin_t(p + "attention.v_proj.weight"))
            layers["wo"].append(lin_t(p + "attention.out_proj.weight"))
            if qkv_bias:
                layers["bq"].append(get(p + "attention.q_proj.bias"))
                layers["bk"].append(get(p + "attention.k_proj.bias"))
                layers["bv"].append(get(p + "attention.v_proj.bias"))
                layers["bo"].append(get(p + "attention.out_proj.bias"))
            layers["ln2"].append(get(p + "rms_norm2.weight"))
            layers["wg"].append(lin_t(p + "ffn.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "ffn.up_proj.weight"))
            layers["wd"].append(lin_t(p + "ffn.down_proj.weight"))

        emb = "vision_tower.transformer.embeddings."
        conv = get(emb + "patch_embedding.weight")           # (H_vis, C, p, p)
        vte = get("visual_embeddings_table.weight")          # (V_vis, H_text)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "patch_b": get(emb + "patch_embedding.bias"),
            "embed_norm": get(emb + "rms_norm.weight"),
            "pos_embed": get(emb + "position_embedding.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "ln_post": get("vision_tower.transformer.rms_norm.weight"),
            "head_w": lin_t("vision_tower.head_linear.weight"),
            "head_norm": get("vision_tower.head_norm.weight"),
            "head_norm_b": get("vision_tower.head_norm.bias"),
            # image soft tokens use the non-indicator vte slice; the tail rows
            # are the indicator embeddings, swapped in at their token positions
            "vte": vte[: vte.shape[0] - n_ind] if n_ind else vte,
            "vte_indicators": vte[vte.shape[0] - n_ind:] if n_ind else vte[:0],
        }

    def _put_vision_params(self, host: Dict) -> None:
        self._indicator_feats = np.asarray(host.pop("vte_indicators"),
                                           np.float32)
        super()._put_vision_params(host)

    def _scatter_features(self, padded, flat_feats):
        """Image soft tokens at image positions + vte rows at the visual
        indicator token positions (HF Ovis2Model.forward's second scatter)."""
        mask, override = super()._scatter_features(padded, flat_feats)
        ind_ids = list(self.config.visual_indicator_token_ids or [])
        if ind_ids and self._indicator_feats is not None \
                and len(self._indicator_feats):
            ids = np.asarray(padded.input_ids)
            for i, tok in enumerate(ind_ids):
                m = ids == tok
                if m.any():
                    override[m] = self._indicator_feats[i]
                    mask = mask | m[..., None]
        return mask, override
