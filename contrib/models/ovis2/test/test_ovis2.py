"""ovis2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/ovis2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_ovis2_generate_matches_hf():
    """Ovis2 visual tokenizer: AIMv2 tower -> 2x2 stride merge -> softmax over
    a visual vocabulary -> soft tokens through the vte; indicator token ids get
    their vte rows swapped in; qwen2 backbone."""
    from transformers import (Ovis2Config, Ovis2ForConditionalGeneration
                              as HFOvis2, Qwen2Config)
    from transformers.models.ovis2.configuration_ovis2 import Ovis2VisionConfig

    from contrib.models.ovis2.src.modeling_ovis2 import (
        Ovis2ForConditionalGeneration)

    vc = Ovis2VisionConfig(hidden_size=32, intermediate_size=64,
                           num_hidden_layers=2, num_attention_heads=2,
                           image_size=16, patch_size=4, num_channels=3,
                           hidden_stride=2, vocab_size=64,
                           num_visual_indicator_tokens=5, qkv_bias=False)
    tc = Qwen2Config(vocab_size=256, hidden_size=24, intermediate_size=48,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, rope_theta=10000.0,
                     tie_word_embeddings=False)
    cfg = Ovis2Config(vision_config=vc, text_config=tc, image_token_id=255,
                      visual_indicator_token_ids=[250, 251, 252, 253, 254],
                      hidden_size=24, vocab_size=256, pad_token_id=0)
    torch.manual_seed(0)
    hf = HFOvis2(cfg).eval()

    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = Ovis2ForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = Ovis2ForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(1)
    ids = rng.integers(1, 250, size=(2, 20))
    ids[:, 2] = 250                                     # img_start indicator
    ids[:, 3:7] = 255                                   # 4 soft tokens/image
    ids[:, 7] = 251                                     # img_end indicator
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor(ids),
                             pixel_values=torch.tensor(pixels),
                             max_new_tokens=8, do_sample=False,
                             pad_token_id=0)
    out = app.generate(ids, pixel_values=pixels, max_new_tokens=8,
                       eos_token_id=-1)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 20:].numpy())
