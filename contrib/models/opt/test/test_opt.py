"""opt parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/opt/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_opt_parity():
    from transformers import OPTConfig, OPTForCausalLM as HFOPT

    from contrib.models.opt.src.modeling_opt import OPTForCausalLM

    cfg = OPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    ffn_dim=128, num_attention_heads=4,
                    max_position_embeddings=128, do_layer_norm_before=True,
                    activation_function="relu", word_embed_proj_dim=64,
                    dropout=0.0)
    torch.manual_seed(0)
    hf = HFOPT(cfg).eval()
    _run_parity(OPTForCausalLM, hf, cfg)
