"""hunyuan parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/hunyuan/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_hunyuan_parity():
    """HunYuan v1 dense: per-head q/k RMSNorm applied AFTER rotary
    (qk_norm_after_rope) over an otherwise llama-shaped GQA block."""
    from transformers import (HunYuanDenseV1Config,
                              HunYuanDenseV1ForCausalLM as HFHunYuan)

    from contrib.models.hunyuan.src.modeling_hunyuan import (
        HunYuanDenseForCausalLM)

    cfg = HunYuanDenseV1Config(vocab_size=256, hidden_size=64,
                               intermediate_size=128, num_hidden_layers=2,
                               num_attention_heads=4, num_key_value_heads=2,
                               head_dim=16, pad_token_id=0,
                               tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFHunYuan(cfg).eval()
    _run_parity(HunYuanDenseForCausalLM, hf, cfg, eos_token_id=2)
