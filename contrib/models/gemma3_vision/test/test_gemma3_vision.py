"""gemma3_vision parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gemma3_vision/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow

from contrib.models.gemma3_vision.test.conftest import tiny_gemma3_vlm  # noqa: F401,E402


def test_gemma3_vision_encoder_matches_hf(tiny_gemma3_vlm):
    """SigLIP tower + gemma3 avg-pool projector: (4,4) patch grid pooled to 4
    tokens, zero-centered soft-emb norm, projection to text hidden."""
    from contrib.models.gemma3_vision.src.modeling_gemma3_vision import (
        Gemma3ForConditionalGeneration)

    hf, cfg = tiny_gemma3_vlm
    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = Gemma3ForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = Gemma3ForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(0)
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    feats = app.encode_images(pixels)                   # (2, 4, H_text)
    with torch.no_grad():
        hf_feats = hf.get_image_features(pixel_values=torch.tensor(pixels))
    np.testing.assert_allclose(feats, np.asarray(hf_feats), atol=3e-4,
                               rtol=1e-3)


def test_gemma3_vision_generate_matches_hf(tiny_gemma3_vlm):
    """Gemma3 VLM greedy decode matches HF CPU; image features merge at
    image-token positions after the sqrt(H) text-embed multiplier."""
    from contrib.models.gemma3_vision.src.modeling_gemma3_vision import (
        Gemma3ForConditionalGeneration)

    hf, cfg = tiny_gemma3_vlm
    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = Gemma3ForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = Gemma3ForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(1)
    ids = rng.integers(1, 250, size=(2, 20))
    ids[:, 2:6] = 255                                   # 4 pooled tokens/image
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor(ids),
                             pixel_values=torch.tensor(pixels),
                             max_new_tokens=8, do_sample=False, pad_token_id=0)
    out = app.generate(ids, pixel_values=pixels, max_new_tokens=8,
                       eos_token_id=-1)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 20:].numpy())
