"""Shared fixture for the gemma3_vision parity tests (conftest so pytest
resolves it both in direct runs and through the tests/ aggregator)."""

import numpy as np  # noqa: F401
import pytest
import torch  # noqa: F401

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403


@pytest.fixture(scope="module")
def tiny_gemma3_vlm():
    from transformers import (Gemma3Config, Gemma3ForConditionalGeneration,
                              Gemma3TextConfig, SiglipVisionConfig)

    vc = SiglipVisionConfig(hidden_size=32, intermediate_size=64,
                            num_hidden_layers=2, num_attention_heads=2,
                            image_size=16, patch_size=4, num_channels=3,
                            vision_use_head=False)
    tc = Gemma3TextConfig(vocab_size=256, hidden_size=48, intermediate_size=96,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, head_dim=16,
                          sliding_window=8, sliding_window_pattern=2,
                          layer_types=["sliding_attention", "full_attention"],
                          rope_theta=10000.0, rope_local_base_freq=10000.0,
                          query_pre_attn_scalar=16.0,
                          tie_word_embeddings=True)
    cfg = Gemma3Config(vision_config=vc, text_config=tc, image_token_index=255,
                       mm_tokens_per_image=4, pad_token_id=0)
    torch.manual_seed(0)
    hf = Gemma3ForConditionalGeneration(cfg).eval()
    return hf, cfg
