"""Gemma 3 VLM (SigLIP tower + Gemma3 text) on the TPU framework (contrib port).

≈ reference `contrib/models/gemma3-vision/` (Gemma3ForConditionalGeneration:
fixed-resolution SigLIP 400M encode + multimodal projector + Gemma3 LLM).
Rides the shared multimodal base (runtime/image_to_text.py). The tower is a
SigLIP ViT: biased patch conv + learned positions (no CLS token), pre-LN
blocks with biased attention and tanh-GELU MLP, final post_layernorm. The
Gemma3 projector then average-pools the patch grid down to
``mm_tokens_per_image`` tokens, applies the zero-centered gemma RMSNorm
(mm_soft_emb_norm), and matmuls into text hidden size
(mm_input_projection_weight). Features land on image-token positions AFTER the
text embedding multiplier (sqrt(H)) is applied to text tokens — matching HF's
masked_scatter of unscaled projected features.
"""

import functools
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.models.gemma3.modeling_gemma3 import (
    Gemma3ForCausalLM, Gemma3InferenceConfig)
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.ops.vit import ViTSpec, vit_encode
from neuronx_distributed_inference_tpu.runtime.image_to_text import (
    ImageToTextInferenceConfig, TpuModelForImageToText)


def siglip_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                         patch_size: int, num_heads: int, eps: float,
                         pool_kernel: int) -> jnp.ndarray:
    """(N, C, H, W) -> (N, mm_tokens, H_text) SigLIP features (shared ViT)
    through the gemma3 avg-pool projector."""
    n = pixel_values.shape[0]
    gh = pixel_values.shape[2] // patch_size
    gw = pixel_values.shape[3] // patch_size
    spec = ViTSpec(patch_size=patch_size, num_heads=num_heads, eps=eps,
                   act="gelu_tanh")
    h = vit_encode(vp, pixel_values, spec)

    # gemma3 projector: avg-pool the (gh, gw) patch grid to tokens_per_side²
    hv = h.shape[-1]
    k = pool_kernel
    grid = h.reshape(n, gh, gw, hv)
    pooled = grid.reshape(n, gh // k, k, gw // k, k, hv).mean(axis=(2, 4))
    pooled = pooled.reshape(n, -1, hv)
    normed = rms_norm(pooled, vp["soft_emb_norm"], eps, zero_centered=True)
    return normed @ vp["proj_w"]


class Gemma3VisionInferenceConfig(ImageToTextInferenceConfig,
                                  Gemma3InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_index")

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        Gemma3InferenceConfig.add_derived_config(self)
        if not hasattr(self, "mm_tokens_per_image") \
                or self.mm_tokens_per_image is None:
            self.mm_tokens_per_image = 256


class Gemma3ForConditionalGeneration(TpuModelForImageToText,
                                     Gemma3ForCausalLM):
    """≈ HF Gemma3ForConditionalGeneration (SigLIP tower + gemma3 text)."""

    @classmethod
    def get_config_cls(cls):
        return Gemma3VisionInferenceConfig

    def vision_encode_fn(self):
        vc = self.config.vision_config
        patches_per_side = vc["image_size"] // vc["patch_size"]
        tokens_per_side = int(self.config.mm_tokens_per_image ** 0.5)
        return functools.partial(
            siglip_vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            eps=vc.get("layer_norm_eps", 1e-6),
            pool_kernel=patches_per_side // tokens_per_side,
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k.startswith("language_model.model."):
                text_sd["model." + k[len("language_model.model."):]] = v
            elif k in ("lm_head.weight", "language_model.lm_head.weight"):
                text_sd["lm_head.weight"] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        def norm_key(k):
            return k[6:] if k.startswith("model.") else k

        state_dict = {norm_key(k): v for k, v in state_dict.items()}
        vc = config.vision_config
        hidden = vc["hidden_size"]

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ("ln1", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
                "ln2", "ln2_b", "w1", "b1", "w2", "b2")
        layers = {k: [] for k in keys}
        for i in range(vc["num_hidden_layers"]):
            p = f"vision_tower.vision_model.encoder.layers.{i}."
            layers["ln1"].append(get(p + "layer_norm1.weight"))
            layers["ln1_b"].append(get(p + "layer_norm1.bias"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.out_proj.weight"))
            layers["bo"].append(get(p + "self_attn.out_proj.bias"))
            layers["ln2"].append(get(p + "layer_norm2.weight"))
            layers["ln2_b"].append(get(p + "layer_norm2.bias"))
            layers["w1"].append(lin_t(p + "mlp.fc1.weight"))
            layers["b1"].append(get(p + "mlp.fc1.bias"))
            layers["w2"].append(lin_t(p + "mlp.fc2.weight"))
            layers["b2"].append(get(p + "mlp.fc2.bias"))

        emb = "vision_tower.vision_model.embeddings."
        conv = get(emb + "patch_embedding.weight")           # (H_vis, C, p, p)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "patch_b": get(emb + "patch_embedding.bias"),
            "pos_embed": get(emb + "position_embedding.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "ln_post": get("vision_tower.vision_model.post_layernorm.weight"),
            "ln_post_b": get("vision_tower.vision_model.post_layernorm.bias"),
            "soft_emb_norm": get(
                "multi_modal_projector.mm_soft_emb_norm.weight"),
            "proj_w": get("multi_modal_projector.mm_input_projection_weight"),
        }
