"""Gemma 3 VLM (SigLIP tower + Gemma3 text) on the TPU framework (contrib port).

≈ reference `contrib/models/gemma3-vision/` (Gemma3ForConditionalGeneration:
fixed-resolution SigLIP 400M encode + multimodal projector + Gemma3 LLM).
Rides the shared multimodal base (runtime/image_to_text.py). The tower is a
SigLIP ViT: biased patch conv + learned positions (no CLS token), pre-LN
blocks with biased attention and tanh-GELU MLP, final post_layernorm. The
Gemma3 projector then average-pools the patch grid down to
``mm_tokens_per_image`` tokens, applies the zero-centered gemma RMSNorm
(mm_soft_emb_norm), and matmuls into text hidden size
(mm_input_projection_weight). Features land on image-token positions AFTER the
text embedding multiplier (sqrt(H)) is applied to text tokens — matching HF's
masked_scatter of unscaled projected features.
"""

import functools
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.models.gemma3.modeling_gemma3 import (
    Gemma3ForCausalLM, Gemma3InferenceConfig)
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import layer_norm, rms_norm
from neuronx_distributed_inference_tpu.runtime.image_to_text import (
    ImageToTextInferenceConfig, TpuModelForImageToText)


def _gelu_tanh(x):
    return jnp.asarray(0.5) * x * (1.0 + jnp.tanh(
        jnp.sqrt(2.0 / jnp.pi) * (x + 0.044715 * x ** 3)))


def siglip_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                         patch_size: int, num_heads: int, eps: float,
                         pool_kernel: int) -> jnp.ndarray:
    """(N, C, H, W) -> (N, mm_tokens, H_text) SigLIP features through the
    gemma3 avg-pool projector."""
    n, c, hh, ww = pixel_values.shape
    gh, gw = hh // patch_size, ww // patch_size
    # patch conv (with bias) as unfold + matmul (stride == kernel)
    x = pixel_values.reshape(n, c, gh, patch_size, gw, patch_size)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(n, gh * gw, -1)
    h = x @ vp["patch_w"] + vp["patch_b"]
    h = h + vp["pos_embed"][None]

    d = h.shape[-1] // num_heads

    def layer(hh, lp):
        x = layer_norm(hh, lp["ln1"], lp["ln1_b"], eps=eps)
        b, s, _ = x.shape
        q = (x @ lp["wq"] + lp["bq"]).reshape(b, s, num_heads, d
                                              ).transpose(0, 2, 1, 3)
        k = (x @ lp["wk"] + lp["bk"]).reshape(b, s, num_heads, d
                                              ).transpose(0, 2, 1, 3)
        v = (x @ lp["wv"] + lp["bv"]).reshape(b, s, num_heads, d
                                              ).transpose(0, 2, 1, 3)
        a = attend(q, k, v)                                # full bidirectional
        a = a.transpose(0, 2, 1, 3).reshape(b, s, -1)
        hh = hh + (a @ lp["wo"] + lp["bo"])
        x = layer_norm(hh, lp["ln2"], lp["ln2_b"], eps=eps)
        hh = hh + (_gelu_tanh(x @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"])
        return hh, None

    import jax
    h, _ = jax.lax.scan(layer, h, vp["layers"])
    h = layer_norm(h, vp["ln_post"], vp["ln_post_b"], eps=eps)

    # gemma3 projector: avg-pool the (gh, gw) patch grid to tokens_per_side²
    hv = h.shape[-1]
    k = pool_kernel
    grid = h.reshape(n, gh, gw, hv)
    pooled = grid.reshape(n, gh // k, k, gw // k, k, hv).mean(axis=(2, 4))
    pooled = pooled.reshape(n, -1, hv)
    normed = rms_norm(pooled, vp["soft_emb_norm"], eps, zero_centered=True)
    return normed @ vp["proj_w"]


class Gemma3VisionInferenceConfig(ImageToTextInferenceConfig,
                                  Gemma3InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_index")

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        Gemma3InferenceConfig.add_derived_config(self)
        if not hasattr(self, "mm_tokens_per_image") \
                or self.mm_tokens_per_image is None:
            self.mm_tokens_per_image = 256


class Gemma3ForConditionalGeneration(TpuModelForImageToText,
                                     Gemma3ForCausalLM):
    """≈ HF Gemma3ForConditionalGeneration (SigLIP tower + gemma3 text)."""

    @classmethod
    def get_config_cls(cls):
        return Gemma3VisionInferenceConfig

    def vision_encode_fn(self):
        vc = self.config.vision_config
        patches_per_side = vc["image_size"] // vc["patch_size"]
        tokens_per_side = int(self.config.mm_tokens_per_image ** 0.5)
        return functools.partial(
            siglip_vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            eps=vc.get("layer_norm_eps", 1e-6),
            pool_kernel=patches_per_side // tokens_per_side,
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k.startswith("language_model.model."):
                text_sd["model." + k[len("language_model.model."):]] = v
            elif k in ("lm_head.weight", "language_model.lm_head.weight"):
                text_sd["lm_head.weight"] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        def norm_key(k):
            return k[6:] if k.startswith("model.") else k

        state_dict = {norm_key(k): v for k, v in state_dict.items()}
        vc = config.vision_config
        hidden = vc["hidden_size"]

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ("ln1", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
                "ln2", "ln2_b", "w1", "b1", "w2", "b2")
        layers = {k: [] for k in keys}
        for i in range(vc["num_hidden_layers"]):
            p = f"vision_tower.vision_model.encoder.layers.{i}."
            layers["ln1"].append(get(p + "layer_norm1.weight"))
            layers["ln1_b"].append(get(p + "layer_norm1.bias"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.out_proj.weight"))
            layers["bo"].append(get(p + "self_attn.out_proj.bias"))
            layers["ln2"].append(get(p + "layer_norm2.weight"))
            layers["ln2_b"].append(get(p + "layer_norm2.bias"))
            layers["w1"].append(lin_t(p + "mlp.fc1.weight"))
            layers["b1"].append(get(p + "mlp.fc1.bias"))
            layers["w2"].append(lin_t(p + "mlp.fc2.weight"))
            layers["b2"].append(get(p + "mlp.fc2.bias"))

        emb = "vision_tower.vision_model.embeddings."
        conv = get(emb + "patch_embedding.weight")           # (H_vis, C, p, p)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "patch_b": get(emb + "patch_embedding.bias"),
            "pos_embed": get(emb + "position_embedding.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "ln_post": get("vision_tower.vision_model.post_layernorm.weight"),
            "ln_post_b": get("vision_tower.vision_model.post_layernorm.bias"),
            "soft_emb_norm": get(
                "multi_modal_projector.mm_soft_emb_norm.weight"),
            "proj_w": get("multi_modal_projector.mm_input_projection_weight"),
        }
