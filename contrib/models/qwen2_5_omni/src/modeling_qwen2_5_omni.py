"""Qwen2.5-Omni (thinker text backbone) on the TPU framework (contrib port).

≈ reference `contrib/models/Qwen2.5-Omni-7B/src/modeling_qwen2_5_omni.py`,
which serves the THINKER's text model only ("focuses on text-only inference",
its line 20; the audio/vision towers and the talker speech head are out of
scope on both sides). The text backbone is qwen2-shaped (GQA, biased qkv,
silu-gated MLP) whose mrope/TMRoPE reduces exactly to standard rope for
text-only inputs (all three mrope sections share the 1D positions). Config
rides nested as ``thinker_config.text_config``; weights carry a
``thinker.model.`` / ``thinker.lm_head`` prefix — both flattened here.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Qwen25OmniInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        # outer omni config: thinker_config -> text_config holds the LM fields;
        # a bare thinker config nests text_config directly
        tc = getattr(self, "thinker_config", None)
        if tc is None and hasattr(self, "text_config"):
            tc = {"text_config": self.text_config}
        if tc is not None:
            if not isinstance(tc, dict):
                tc = tc.to_dict()
            inner = tc.get("text_config", tc)
            if not isinstance(inner, dict):
                inner = inner.to_dict()
            for k, v in inner.items():
                if not k.startswith("_"):
                    setattr(self, k, v)
            if getattr(self, "pad_token_id", None) is None:
                self.pad_token_id = tc.get("pad_token_id")
        for attr, default in (("rope_theta", 1000000.0),
                              ("rms_norm_eps", 1e-6),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class Qwen25OmniThinkerForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Qwen25OmniInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_bias=True,            # qwen2-style biased qkv
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # mrope with identical t/h/w positions == standard rope (text-only)
        return rope_ops.default_inv_freq(config.head_dim,
                                         float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def flat_key(k):
            for pre in ("model.thinker.model.", "thinker.model."):
                if k.startswith(pre):
                    return "model." + k[len(pre):]
            for pre in ("model.thinker.lm_head.", "thinker.lm_head."):
                if k.startswith(pre):
                    return "lm_head." + k[len(pre):]
            return k

        state_dict = {flat_key(k): v for k, v in state_dict.items()}

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "bq", "bk", "bv",
                                  "wo", "ln2", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
