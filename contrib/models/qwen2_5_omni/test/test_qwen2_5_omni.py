"""qwen2_5_omni parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/qwen2_5_omni/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_qwen2_5_omni_thinker_parity():
    """Qwen2.5-Omni thinker text backbone (matches the reference contrib's
    text-only scope): qwen2-shaped GQA with biased qkv; mrope with shared 1D
    positions == standard rope."""
    from transformers import Qwen2_5OmniThinkerConfig
    from transformers.models.qwen2_5_omni.modeling_qwen2_5_omni import (
        Qwen2_5OmniThinkerForConditionalGeneration as HFThinker)

    from contrib.models.qwen2_5_omni.src.modeling_qwen2_5_omni import (
        Qwen25OmniThinkerForCausalLM)

    cfg = Qwen2_5OmniThinkerConfig(
        text_config=dict(vocab_size=256, hidden_size=32, intermediate_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, rope_theta=10000.0,
                         rope_scaling={"mrope_section": [2, 1, 1],
                                       "rope_type": "default",
                                       "type": "default"},
                         tie_word_embeddings=False),
        audio_config=dict(d_model=16, encoder_layers=1,
                          encoder_attention_heads=2, encoder_ffn_dim=32,
                          num_mel_bins=8, max_source_positions=10, n_window=2,
                          output_dim=32),
        vision_config=dict(hidden_size=16, intermediate_size=32, depth=2,
                           num_heads=2, patch_size=4, spatial_merge_size=1,
                           temporal_patch_size=1, out_hidden_size=32,
                           fullatt_block_indexes=[1], window_size=8),
        vision_start_token_id=251, vision_end_token_id=252,
        audio_start_token_id=253, audio_end_token_id=254,
        image_token_id=255, video_token_id=250, audio_token_id=249,
        position_id_per_seconds=25, seconds_per_chunk=2, pad_token_id=0,
    )
    torch.manual_seed(0)
    hf = HFThinker(cfg).eval()

    config = Qwen25OmniThinkerForCausalLM.get_config_cls()(
        _tpu_cfg(), load_config=load_pretrained_config(cfg.to_dict()))
    app = Qwen25OmniThinkerForCausalLM(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))

    rng = np.random.default_rng(0)
    ids = rng.integers(3, 249, size=(2, 12)).astype(np.int64)
    with torch.no_grad():
        hf_out = hf.generate(torch.tensor(ids), max_new_tokens=8,
                             do_sample=False, pad_token_id=0)
    out = app.generate(ids, max_new_tokens=8, eos_token_id=-1)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 12:].numpy())
