"""persimmon parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/persimmon/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_persimmon_parity():
    """Persimmon: per-head q/k LayerNorm (biased), per-head-interleaved fused
    qkv unpacked at conversion, relu2 plain MLP, partial rotary."""
    from transformers import PersimmonConfig, PersimmonForCausalLM as HFPersimmon

    from contrib.models.persimmon.src.modeling_persimmon import (
        PersimmonForCausalLM)

    cfg = PersimmonConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          partial_rotary_factor=0.5, qk_layernorm=True,
                          hidden_act="relu2", pad_token_id=0,
                          tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFPersimmon(cfg).eval()
    _run_parity(PersimmonForCausalLM, hf, cfg)
