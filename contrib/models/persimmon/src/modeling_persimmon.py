"""Persimmon (Adept 8B) on the TPU framework (contrib port).

Fully-biased decoder with per-head q/k LayerNorm (qk_norm_type="layer"),
half-width partial rotary (theta 25000), squared-ReLU plain MLP, biased
LayerNorms, and a per-head-interleaved fused query_key_value projection
([q|k|v] within each head's 3*d block, unpacked at conversion).
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class PersimmonInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 25000.0), ("layer_norm_eps", 1e-5),
                              ("partial_rotary_factor", 0.5),
                              ("qk_layernorm", True), ("hidden_act", "relu2"),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "num_key_value_heads") \
                or self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class PersimmonForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return PersimmonInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            norm_type="layer",
            norm_bias=True,
            activation=config.hidden_act,
            mlp_kind="plain",
            mlp_bias=True,
            attention_bias=True,
            o_bias=True,
            qk_norm=bool(config.qk_layernorm),
            qk_norm_type="layer",
            rotary_dim=int(config.head_dim * float(config.partial_rotary_factor)),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        rd = int(config.head_dim * float(config.partial_rotary_factor))
        return rope_ops.default_inv_freq(rd, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        H = config.hidden_size
        n = config.num_attention_heads
        d = config.head_dim
        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv",
                                  "bq", "bk", "bv", "wo", "bo",
                                  "q_norm", "q_norm_b", "k_norm", "k_norm_b",
                                  "ln2", "ln2_b", "wg", "bg", "wd", "bd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            # query_key_value packs [q|k|v] per head: (H, n, 3, d) in x@w layout
            qkv = lin_t(p + "self_attn.query_key_value.weight").reshape(H, n, 3, d)
            bias = get(p + "self_attn.query_key_value.bias").reshape(n, 3, d)
            layers["wq"].append(np.ascontiguousarray(qkv[:, :, 0].reshape(H, n * d)))
            layers["wk"].append(np.ascontiguousarray(qkv[:, :, 1].reshape(H, n * d)))
            layers["wv"].append(np.ascontiguousarray(qkv[:, :, 2].reshape(H, n * d)))
            layers["bq"].append(np.ascontiguousarray(bias[:, 0].reshape(-1)))
            layers["bk"].append(np.ascontiguousarray(bias[:, 1].reshape(-1)))
            layers["bv"].append(np.ascontiguousarray(bias[:, 2].reshape(-1)))
            layers["wo"].append(lin_t(p + "self_attn.dense.weight"))
            layers["bo"].append(get(p + "self_attn.dense.bias"))
            layers["q_norm"].append(get(p + "self_attn.q_layernorm.weight"))
            layers["q_norm_b"].append(get(p + "self_attn.q_layernorm.bias"))
            layers["k_norm"].append(get(p + "self_attn.k_layernorm.weight"))
            layers["k_norm_b"].append(get(p + "self_attn.k_layernorm.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["wg"].append(lin_t(p + "mlp.dense_h_to_4h.weight"))
            layers["bg"].append(get(p + "mlp.dense_h_to_4h.bias"))
            layers["wd"].append(lin_t(p + "mlp.dense_4h_to_h.weight"))
            layers["bd"].append(get(p + "mlp.dense_4h_to_h.bias"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.final_layernorm.weight"),
            "final_norm_b": get("model.final_layernorm.bias"),
            "lm_head": lin_t("lm_head.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
