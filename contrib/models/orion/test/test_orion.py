"""orion parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/orion/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_orion_parity():
    """Orion: llama geometry with BIASED LayerNorm everywhere instead of
    RMSNorm (norm_type=layer + norm_bias)."""
    from contrib.models.orion.src.modeling_orion import OrionForCausalLM

    cfg = dict(model_type="orion", vocab_size=256, hidden_size=64,
               intermediate_size=128, num_hidden_layers=2,
               num_attention_heads=4, num_key_value_heads=4,
               rms_norm_eps=1e-5, rope_theta=10000.0,
               tie_word_embeddings=False)
    torch.manual_seed(0)
    oracle = _OracleModel(256, 64, 128, 2, 4, 4, 16, eps=1e-5,
                          norm="layer").eval()
    with torch.no_grad():
        for n, p in oracle.named_parameters():
            if "layernorm.bias" in n or n == "model.norm.bias":
                p.copy_(torch.randn_like(p) * 0.1)
    _run_parity_oracle(OrionForCausalLM, oracle, cfg)
