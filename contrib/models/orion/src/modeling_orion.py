"""Orion (OrionStar AI, 14B) on the TPU framework (contrib port).

≈ reference `contrib/models/orion-14b-chat/src/modeling_orion.py`. Llama
geometry and rope, but every norm is a standard *biased* LayerNorm
(input/post-attention/final) instead of RMSNorm; silu-gated MLP, no
attention/MLP biases, untied lm_head. Maps onto the shared core via
norm_type="layer" + norm_bias=True.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class OrionInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "num_key_value_heads") \
                or self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class OrionForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return OrionInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            norm_type="layer",
            norm_bias=True,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim,
                                         float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "wo",
                                  "ln2", "ln2_b", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "final_norm_b": get("model.norm.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
