"""trinity parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/trinity/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""

import math  # noqa: F401

import numpy as np
import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


class _TrinityOracleLayer(torch.nn.Module):
    def __init__(self, H, nq, nkv, d, I_dense, I_moe, E, eps, dense):
        super().__init__()
        rms = lambda n: _OracleRMSNorm(n, eps)  # noqa: E731
        self.input_layernorm = rms(H)
        self.post_attention_layernorm = rms(H)
        self.pre_mlp_layernorm = rms(H)
        self.post_mlp_layernorm = rms(H)
        sa = torch.nn.Module()
        sa.q_proj = torch.nn.Linear(H, nq * d, bias=False)
        sa.k_proj = torch.nn.Linear(H, nkv * d, bias=False)
        sa.v_proj = torch.nn.Linear(H, nkv * d, bias=False)
        sa.o_proj = torch.nn.Linear(nq * d, H, bias=False)
        sa.q_norm = rms(d)
        sa.k_norm = rms(d)
        sa.gate_proj = torch.nn.Linear(H, nq, bias=False)  # one gate per head
        self.self_attn = sa
        mlp = torch.nn.Module()
        if dense:
            mlp.gate_proj = torch.nn.Linear(H, I_dense, bias=False)
            mlp.up_proj = torch.nn.Linear(H, I_dense, bias=False)
            mlp.down_proj = torch.nn.Linear(I_dense, H, bias=False)
        else:
            router = torch.nn.Module()
            router.gate = torch.nn.Linear(H, E, bias=False)
            mlp.router = router
            mlp.expert_bias = torch.nn.Parameter(torch.zeros(E))
            mlp.experts = torch.nn.ModuleList()
            for _ in range(E):
                ex = torch.nn.Module()
                ex.gate_proj = torch.nn.Linear(H, I_moe, bias=False)
                ex.up_proj = torch.nn.Linear(H, I_moe, bias=False)
                ex.down_proj = torch.nn.Linear(I_moe, H, bias=False)
                mlp.experts.append(ex)
            sh = torch.nn.Module()
            sh.gate_proj = torch.nn.Linear(H, I_moe, bias=False)
            sh.up_proj = torch.nn.Linear(H, I_moe, bias=False)
            sh.down_proj = torch.nn.Linear(I_moe, H, bias=False)
            mlp.shared_experts = sh
        self.mlp = mlp
        self.dense = dense


class _TrinityOracle(torch.nn.Module):
    """Independent AFMoE oracle: sliding(rope)/full(NoPE) attention with a
    per-head sigmoid gate, 4-norm sandwich blocks, sigmoid+bias routing with
    renormalized unbiased gates × route_scale, shared expert, muP embeds."""

    def __init__(self, V, H, L, nq, nkv, d, I_dense, I_moe, E, topk, window,
                 layer_kinds, num_dense, route_scale=1.0, eps=1e-5):
        super().__init__()
        inner = torch.nn.Module()
        inner.embed_tokens = torch.nn.Embedding(V, H)
        inner.layers = torch.nn.ModuleList(
            [_TrinityOracleLayer(H, nq, nkv, d, I_dense, I_moe, E, eps,
                                 i < num_dense) for i in range(L)])
        inner.norm = _OracleRMSNorm(H, eps)
        self.model = inner
        self.lm_head = torch.nn.Linear(H, V, bias=False)
        self.nq, self.nkv, self.d, self.topk = nq, nkv, d, topk
        self.window, self.kinds, self.route_scale = window, layer_kinds, route_scale
        self.mup = math.sqrt(H)
        self.inv_freq = (10000.0 ** (-np.arange(0, d, 2) / d)).astype(np.float32)

    def _attn(self, lyr, x, use_rope):
        B, S, _ = x.shape
        sa = lyr.self_attn
        q = sa.q_proj(x).view(B, S, self.nq, self.d).transpose(1, 2)
        k = sa.k_proj(x).view(B, S, self.nkv, self.d).transpose(1, 2)
        v = sa.v_proj(x).view(B, S, self.nkv, self.d).transpose(1, 2)
        q, k = sa.q_norm(q), sa.k_norm(k)
        if use_rope:
            pos = torch.arange(S, dtype=torch.float32)
            freqs = torch.outer(pos, torch.tensor(self.inv_freq))
            emb = torch.cat([freqs, freqs], dim=-1)
            cos, sin = emb.cos()[None, None], emb.sin()[None, None]

            def rot(t):
                h = t.shape[-1] // 2
                return torch.cat([-t[..., h:], t[..., :h]], dim=-1)

            q = q * cos + rot(q) * sin
            k = k * cos + rot(k) * sin
        rep = self.nq // self.nkv
        k = k.repeat_interleave(rep, dim=1)
        v = v.repeat_interleave(rep, dim=1)
        scores = (q @ k.transpose(-1, -2)) / math.sqrt(self.d)
        pos = torch.arange(S)
        mask = pos[None, :] <= pos[:, None]
        if use_rope:  # sliding layers additionally window the mask
            mask &= pos[None, :] > pos[:, None] - self.window
        scores = scores.masked_fill(~mask, float("-inf"))
        attn = torch.softmax(scores, dim=-1) @ v            # (B, nq, S, d)
        gate = torch.sigmoid(sa.gate_proj(x))               # (B, S, nq)
        attn = attn * gate.transpose(1, 2)[..., None]
        return sa.o_proj(attn.transpose(1, 2).reshape(B, S, -1))

    def _moe(self, mlp, x):
        B, S, H = x.shape
        flat = x.reshape(-1, H)
        scores = torch.sigmoid(mlp.router.gate(flat).float())
        _, idx = torch.topk(scores + mlp.expert_bias.float()[None], self.topk)
        w = torch.gather(scores, 1, idx)
        w = w / w.sum(-1, keepdim=True)
        w = w * self.route_scale
        out = torch.zeros_like(flat)
        for n in range(flat.shape[0]):
            for j in range(self.topk):
                ex = mlp.experts[idx[n, j]]
                h = torch.nn.functional.silu(ex.gate_proj(flat[n])) * ex.up_proj(flat[n])
                out[n] += w[n, j] * ex.down_proj(h)
        sh = mlp.shared_experts
        shared = sh.down_proj(torch.nn.functional.silu(sh.gate_proj(flat))
                              * sh.up_proj(flat))
        return (out + shared).reshape(B, S, H)

    def forward(self, ids):
        h = self.model.embed_tokens(ids) * self.mup
        for i, lyr in enumerate(self.model.layers):
            x = lyr.input_layernorm(h)
            a = self._attn(lyr, x, use_rope=(self.kinds[i] == "sliding_attention"))
            h = h + lyr.post_attention_layernorm(a)
            x = lyr.pre_mlp_layernorm(h)
            m = (lyr.mlp.down_proj(torch.nn.functional.silu(lyr.mlp.gate_proj(x))
                                   * lyr.mlp.up_proj(x))
                 if lyr.dense else self._moe(lyr.mlp, x))
            h = h + lyr.post_mlp_layernorm(m)
        return self.lm_head(self.model.norm(h))


def test_trinity_parity():
    """Trinity/AFMoE: mixed sliding(rope)/full(NoPE) attention with per-head
    sigmoid output gates, 4-norm blocks, first-2-dense then sigmoid+expert-bias
    MoE with shared expert, muP embedding scale, route_scale=2."""
    from contrib.models.trinity.src.modeling_trinity import TrinityForCausalLM

    kinds = ["sliding_attention", "sliding_attention", "full_attention",
             "sliding_attention"]
    cfg = dict(model_type="afmoe", vocab_size=256, hidden_size=64,
               num_hidden_layers=4, num_attention_heads=4,
               num_key_value_heads=2, head_dim=16, intermediate_size=128,
               moe_intermediate_size=32, num_local_experts=8,
               num_experts_per_tok=2, num_dense_layers=2, sliding_window=8,
               layer_types=kinds, route_scale=2.0, rms_norm_eps=1e-5,
               rope_theta=10000.0, mup_enabled=True, tie_word_embeddings=False)
    torch.manual_seed(0)
    oracle = _TrinityOracle(256, 64, 4, 4, 2, 16, 128, 32, 8, 2, 8,
                            kinds, 2, route_scale=2.0).eval()
    with torch.no_grad():
        for lyr in oracle.model.layers:
            if not lyr.dense:
                lyr.mlp.expert_bias.copy_(torch.randn(8) * 0.5)
    _run_parity_oracle(TrinityForCausalLM, oracle, cfg, atol=2e-3)
