"""Trinity (Arcee AFMoE family: Nano/Mini/Large) on the TPU framework
(contrib port).

≈ reference `contrib/models/Trinity/src/modeling_trinity.py` (AfmoeForCausalLM).
The architecture stacks four independent features on a GQA decoder:

- **Mixed attention**: a sliding/full layer pattern where sliding layers use
  rope and a windowed causal mask, while full-attention layers are NoPE
  (no rotary at all) with a plain causal mask.
- **Gated attention**: a per-HEAD sigmoid gate projected from the normed layer
  input (gate_proj: hidden -> num_heads, one scalar per head) multiplies the
  attention output before o_proj.
- **Dual norms** (4 RMSNorms/layer): input_layernorm -> attn ->
  post_attention_layernorm -> +residual; pre_mlp_layernorm -> MLP/MoE ->
  post_mlp_layernorm -> +residual; plus per-head q/k RMSNorm before rope.
- **Mixed dense/MoE**: the first num_dense_layers use a dense silu-gated MLP;
  the rest route 128+ experts with SIGMOID scores, top-k selected on
  scores + expert_bias (bias affects selection only), gates = the unbiased
  scores renormalized to sum 1, times route_scale — plus one ungated shared
  expert added densely. muP: embeddings scaled by sqrt(hidden_size).
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs, moe_block
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class TrinityArchArgs(ModelArchArgs):
    layer_kinds: Tuple[str, ...] = ()      # "sliding" | "full" per layer
    mlp_kinds: Tuple[str, ...] = ()        # "dense" | "moe" per layer
    mup_embed_scale: float = 1.0


def _attention(lp, args: TrinityArchArgs, hn, cos, sin, mask, k_cache, v_cache,
               positions, bucket, use_rope: bool):
    b, t, _ = hn.shape
    nq, nkv, d = args.num_heads, args.num_kv_heads, args.head_dim
    q = (hn @ lp["wq"]).reshape(b, t, nq, d).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, t, nkv, d).transpose(0, 2, 1, 3)
    v = (hn @ lp["wv"]).reshape(b, t, nkv, d).transpose(0, 2, 1, 3)
    q = rms_norm(q, lp["q_norm"], args.rms_norm_eps)
    k = rms_norm(k, lp["k_norm"], args.rms_norm_eps)
    if use_rope:
        q, k = rope_ops.apply_rotary(q, k, cos, sin)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask)              # (B, nq, T, d)
    # per-head sigmoid gate from the normed layer input: (B, T, nq) scalars
    gate = jax.nn.sigmoid(hn @ lp["w_attn_gate"])
    attn = attn * gate.transpose(0, 2, 1)[..., None]
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, nq * d)
    return attn @ lp["wo"], k_cache, v_cache


def _dense_mlp(lp, hn):
    return (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]


def _moe_mlp(lp, args: TrinityArchArgs, hn, mesh, rules, decode):
    """Sigmoid routing, selection-only expert bias, renormalized unbiased gates
    × route_scale, ungated shared expert — the shared `ops/moe.moe_block` with
    router_mode="sigmoid_group" (n_group=1) + router_cb covers all of it, and
    carries the EP/TP sharding constraints on the expert intermediates."""
    return moe_block(lp, args, hn, mesh, rules, jax.nn.silu, decode=decode)


def _forward(params, args: TrinityArchArgs, h, cos, sin, full_mask,
             sliding_mask, cache, positions, bucket, mesh=None, rules=None):
    ks, vs = [], []
    for idx, kind in enumerate(args.layer_kinds):
        lp = params["layers"][idx]
        resid = h
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        mask = sliding_mask if kind == "sliding" else full_mask
        out, kc, vc = _attention(lp, args, hn, cos, sin, mask,
                                 cache["k"][idx], cache["v"][idx], positions,
                                 bucket, use_rope=(kind == "sliding"))
        ks.append(kc)
        vs.append(vc)
        h = resid + rms_norm(out, lp["ln_post_attn"], args.rms_norm_eps)
        resid = h
        hn = rms_norm(h, lp["ln_pre_mlp"], args.rms_norm_eps)
        mlp_out = (_dense_mlp(lp, hn) if args.mlp_kinds[idx] == "dense"
                   else _moe_mlp(lp, args, hn, mesh, rules,
                                 decode=positions is not None))
        h = resid + rms_norm(mlp_out, lp["ln_post_mlp"], args.rms_norm_eps)
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    return h, {"k": jnp.stack(ks), "v": jnp.stack(vs)}


def prefill_forward(params, args: TrinityArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None,
                    use_flash=False, adapter_ids=None, use_ring=False,
                    return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0) * args.mup_embed_scale
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    kv_pos = position_ids[:, None, None, :]
    q_pos = position_ids[:, None, :, None]
    sliding = jnp.logical_and(mask, kv_pos > q_pos - args.sliding_window)
    h, out_cache = _forward(params, args, h, cos, sin, mask, sliding, cache,
                            None, None, mesh=mesh, rules=rules)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["lm_head"]).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: TrinityArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None,
                   adapter_ids=None, tree=None, return_hidden=False,
                   **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Trinity decode is single-token only in this port")
    h = jnp.take(params["embed"], input_ids, axis=0) * args.mup_embed_scale
    pos_grid = position_ids[:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    q_pos = pos_grid[:, None, :, None]
    mask = kv_pos <= q_pos
    sliding = jnp.logical_and(mask, kv_pos > q_pos - args.sliding_window)
    h, out_cache = _forward(params, args, h, cos, sin, mask, sliding, cache,
                            position_ids, decode_bucket, mesh=mesh,
                            rules=rules)
    logits = (h @ params["lm_head"]).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class TrinityInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "moe_intermediate_size", "num_local_experts",
                           "num_experts_per_tok", "layer_types")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("sliding_window", 2048),
                              ("num_dense_layers", 2),
                              ("route_scale", 1.0), ("mup_enabled", True),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class TrinityForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "Trinity (AFMoE)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return TrinityInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> TrinityArchArgs:
        import math
        return TrinityArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            sliding_window=int(config.sliding_window),
            tie_word_embeddings=bool(config.tie_word_embeddings),
            layer_kinds=tuple("sliding" if t == "sliding_attention" else "full"
                              for t in config.layer_types),
            mlp_kinds=tuple("dense" if i < config.num_dense_layers else "moe"
                            for i in range(config.num_hidden_layers)),
            moe=MoEArgs(
                num_experts=int(config.num_local_experts),
                experts_per_tok=int(config.num_experts_per_tok),
                router_mode="sigmoid_group",
                n_group=1,
                topk_group=1,
                score_correction_bias=True,
                norm_topk_prob=True,
                routed_scaling_factor=float(config.route_scale),
                shared_expert_intermediate_size=int(
                    config.moe_intermediate_size),
                shared_expert_gated=False,
            ),
            mup_embed_scale=(math.sqrt(config.hidden_size)
                             if config.mup_enabled else 1.0),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim,
                                         float(config.rope_theta))

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: TrinityArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "k": jnp.zeros((a.num_layers, b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((a.num_layers, b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        params = jax.tree.map(_put, host_params)
        params["rope_inv_freq"] = jax.device_put(
            np.asarray(host_params["rope_inv_freq"], np.float32))
        for i, lp in enumerate(params["layers"]):
            if "router_cb" in lp:     # selection bias stays fp32
                lp["router_cb"] = jax.device_put(np.asarray(
                    host_params["layers"][i]["router_cb"], np.float32))
        self.params = params
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        E = config.num_local_experts
        layers = []
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            lp: Dict[str, np.ndarray] = {
                "ln1": get(p + "input_layernorm.weight"),
                "ln_post_attn": get(p + "post_attention_layernorm.weight"),
                "ln_pre_mlp": get(p + "pre_mlp_layernorm.weight"),
                "ln_post_mlp": get(p + "post_mlp_layernorm.weight"),
                "wq": lin_t(p + "self_attn.q_proj.weight"),
                "wk": lin_t(p + "self_attn.k_proj.weight"),
                "wv": lin_t(p + "self_attn.v_proj.weight"),
                "wo": lin_t(p + "self_attn.o_proj.weight"),
                "q_norm": get(p + "self_attn.q_norm.weight"),
                "k_norm": get(p + "self_attn.k_norm.weight"),
                # per-head gate: (num_heads, hidden) in HF layout
                "w_attn_gate": lin_t(p + "self_attn.gate_proj.weight"),
            }
            if i < config.num_dense_layers:
                lp["wg"] = lin_t(p + "mlp.gate_proj.weight")
                lp["wu"] = lin_t(p + "mlp.up_proj.weight")
                lp["wd"] = lin_t(p + "mlp.down_proj.weight")
            else:
                m = p + "mlp."
                lp["router"] = lin_t(m + "router.gate.weight")
                lp["router_cb"] = get(m + "expert_bias")
                lp["wg"] = np.stack(
                    [lin_t(m + f"experts.{e}.gate_proj.weight")
                     for e in range(E)])
                lp["wu"] = np.stack(
                    [lin_t(m + f"experts.{e}.up_proj.weight")
                     for e in range(E)])
                lp["wd"] = np.stack(
                    [lin_t(m + f"experts.{e}.down_proj.weight")
                     for e in range(E)])
                lp["shared_wg"] = lin_t(m + "shared_experts.gate_proj.weight")
                lp["shared_wu"] = lin_t(m + "shared_experts.up_proj.weight")
                lp["shared_wd"] = lin_t(m + "shared_experts.down_proj.weight")
            layers.append(lp)
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": layers,
            "final_norm": get("model.norm.weight"),
            "lm_head": (lin_t("lm_head.weight")
                        if not config.tie_word_embeddings
                        else np.ascontiguousarray(
                            get("model.embed_tokens.weight").T)),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
