"""granitemoe parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/granitemoe/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_granitemoe_parity():
    from transformers import (GraniteMoeConfig,
                              GraniteMoeForCausalLM as HFGraniteMoe)

    from contrib.models.granitemoe.src.modeling_granitemoe import (
        GraniteMoeForCausalLM)

    cfg = GraniteMoeConfig(vocab_size=256, hidden_size=64, intermediate_size=96,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=2, num_local_experts=4,
                           num_experts_per_tok=2, embedding_multiplier=6.0,
                           attention_multiplier=0.0625, residual_multiplier=0.3,
                           logits_scaling=4.0, pad_token_id=0,
                           tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFGraniteMoe(cfg).eval()
    _run_parity(GraniteMoeForCausalLM, hf, cfg, atol=1e-3, rtol=1e-3)
