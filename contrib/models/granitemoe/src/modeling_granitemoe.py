"""GraniteMoe (IBM Granite 3.x MoE) on the TPU framework (contrib port).

Granite's scaling quartet (embedding/attention/residual multipliers + logits
scaling) over a fused-projection MoE: per-expert input_linear packs gate|up
(split at conversion), routing is top-k-then-softmax over the selected logits
(ops/moe.py router_mode="topk_softmax").
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class GraniteMoeInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "num_local_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        defaults = (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                    ("embedding_multiplier", 1.0), ("attention_multiplier", None),
                    ("residual_multiplier", 1.0), ("logits_scaling", 1.0),
                    ("tie_word_embeddings", False), ("attention_bias", False))
        for attr, default in defaults:
            if not hasattr(self, attr) or getattr(self, attr) is None:
                if default is not None or not hasattr(self, attr):
                    setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class GraniteMoeForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GraniteMoeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_scale=config.attention_multiplier,
            embedding_multiplier=float(config.embedding_multiplier),
            residual_multiplier=float(config.residual_multiplier),
            logits_scale=1.0 / float(config.logits_scaling),
            attention_bias=bool(config.attention_bias),
            moe=MoEArgs(num_experts=config.num_local_experts,
                        experts_per_tok=config.num_experts_per_tok,
                        router_mode="topk_softmax"),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        I = config.intermediate_size
        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "router", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            m = p + "block_sparse_moe."
            layers["router"].append(lin_t(m + "router.layer.weight"))
            # input_linear (E, 2I, H): rows [0:I] = gate, [I:2I] = up
            fused = get(m + "input_linear.weight")
            layers["wg"].append(np.ascontiguousarray(
                fused[:, :I, :].transpose(0, 2, 1)))
            layers["wu"].append(np.ascontiguousarray(
                fused[:, I:, :].transpose(0, 2, 1)))
            layers["wd"].append(np.ascontiguousarray(
                get(m + "output_linear.weight").transpose(0, 2, 1)))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
