"""minimax parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/minimax/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_minimax_parity():
    """MiniMax lightning/linear-attention hybrid: decayed KV-state linear
    attention (scan-over-blocks prefill, (B,h,d,d) fp32 state cache) alternating
    with full softmax attention, MoE every layer, normed residual stream."""
    from transformers import MiniMaxConfig, MiniMaxForCausalLM as HFMiniMax

    from contrib.models.minimax.src.modeling_minimax import MiniMaxForCausalLM

    cfg = MiniMaxConfig(vocab_size=256, hidden_size=64, intermediate_size=96,
                        num_hidden_layers=4, num_attention_heads=4,
                        num_key_value_heads=2, head_dim=16,
                        num_local_experts=4, num_experts_per_tok=2,
                        block_size=8,
                        layer_types=["linear_attention", "full_attention",
                                     "linear_attention", "full_attention"],
                        pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFMiniMax(cfg).eval()
    _run_parity(MiniMaxForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
