"""MiniMax-Text (lightning/linear-attention hybrid MoE) on the TPU framework
(contrib port).

The hub's linear-attention family: alternating FULL softmax-attention layers
(standard GQA + rope + KV cache) and LIGHTNING attention layers — per-head
exponentially-decayed linear attention whose state is a (B, heads, d, d) fp32
KV outer-product matrix, not a KV cache. TPU redesign:

- Prefill runs the block formulation as a `jax.lax.scan` over sequence blocks
  with the state matrix as carry: intra-block (QKᵀ ⊙ decay) V plus inter-block
  (Q ⊙ q_decay) S, then S ← S·e^{-λB} + (K ⊙ k_decay)ᵀ V — the HF reference's
  Python block loop, expressed as a compiled scan.
- Right padding: padded V rows are zeroed (their outer products vanish), and
  the carried state is rescaled by e^{+λ·pad} per row afterwards so decode
  resumes with exactly the true-length state.
- Decode is one fused update: S ← e^{-λ}S + kᵀv; out = qS.
- The block output is RMS-normed, sigmoid-gated from the hidden state, and
  projected; every layer's FFN is a Mixtral-style MoE (softmax-topk-renorm);
  the residual stream itself is normed each layer with the alpha/beta factors.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class MiniMaxArchArgs(ModelArchArgs):
    layer_kinds: Tuple[str, ...] = ()     # "full" | "linear" per layer
    block_size: int = 256
    num_experts: int = 8
    experts_per_tok: int = 2
    attn_alpha: float = 1.0
    attn_beta: float = 1.0
    mlp_alpha: float = 1.0
    mlp_beta: float = 1.0


def _slope_rate(num_heads: int, layer_idx: int, num_layers: int) -> np.ndarray:
    """Per-head lightning decay rates (HF `get_slope_rate`)."""
    base = 1.0 / (2.0 ** (8.0 / num_heads))
    rate = base ** (np.arange(num_heads) + 1)
    factor = 1.0 - layer_idx / (num_layers - 1 + 1e-5) + 1e-5
    return (rate * factor).astype(np.float32)            # (h,)


def _lightning_prefill(lp, hn, args, last_token_idx, slope):
    """Blocked linear attention over the full sequence.
    Returns (out (B, T, H), state (B, h, d, d) fp32 at each row's true length)."""
    b, t, _ = hn.shape
    n, d = args.num_heads, args.head_dim
    qkv = jax.nn.silu(hn @ lp["wqkv"]).reshape(b, t, n, 3 * d)
    q = qkv[..., :d].transpose(0, 2, 1, 3)               # (B, h, T, d)
    k = qkv[..., d : 2 * d].transpose(0, 2, 1, 3)
    v = qkv[..., 2 * d :].transpose(0, 2, 1, 3)
    # zero padded V rows: their KV outer products then vanish from the state
    valid = (jnp.arange(t)[None, :] <= last_token_idx[:, None])
    v = jnp.where(valid[:, None, :, None], v, 0.0)

    bs = min(args.block_size, t)
    pad = (-t) % bs
    if pad:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    nb = (t + pad) // bs
    sl = slope[None, :, None, None]                      # (1, h, 1, 1)
    rng_b = jnp.arange(bs, dtype=jnp.float32) + 1.0
    q_decay = jnp.exp(-sl * rng_b[None, None, :, None])            # (1,h,bs,1)
    k_decay = jnp.exp(-sl * (bs - rng_b)[None, None, :, None])     # (1,h,bs,1)
    diff = rng_b[:, None] - rng_b[None, :]
    diag_decay = jnp.exp(jnp.where(diff >= 0, -sl * diff[None, None], -jnp.inf))
    block_decay = jnp.exp(-slope * bs)[None, :, None, None]        # (1,h,1,1)

    qb = q.reshape(b, n, nb, bs, d).transpose(2, 0, 1, 3, 4)
    kb = k.reshape(b, n, nb, bs, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, n, nb, bs, d).transpose(2, 0, 1, 3, 4)

    def body(s, xs):
        qi, ki, vi = xs                                  # (B, h, bs, d)
        qi32 = qi.astype(jnp.float32)
        ki32 = ki.astype(jnp.float32)
        vi32 = vi.astype(jnp.float32)
        intra = jnp.einsum("bhsd,bhtd->bhst", qi32, ki32) * diag_decay
        out = (jnp.einsum("bhst,bhtd->bhsd", intra, vi32)
               + jnp.einsum("bhsd,bhde->bhse", qi32 * q_decay, s))
        s = s * block_decay + jnp.einsum("bhsd,bhse->bhde", ki32 * k_decay, vi32)
        return s, out

    s0 = jnp.zeros((b, n, d, d), jnp.float32)
    state, outs = jax.lax.scan(body, s0, (qb, kb, vb))
    out = outs.transpose(1, 2, 0, 3, 4).reshape(b, n, nb * bs, d)[:, :, :t]

    # undo the decay the padded tail applied to the state: padded rows added
    # nothing (v=0) but the per-block e^{-λ·bs} factors still ran over them
    pad_len = (t + pad - 1) - last_token_idx.astype(jnp.float32)   # (B,)
    state = state * jnp.exp(slope[None, :, None, None]
                            * pad_len[:, None, None, None])
    return _finish_lightning(lp, hn, out), state


def _lightning_decode(lp, hn, args, state, slope):
    """One-token lightning step. hn (B, 1, H); state (B, h, d, d) fp32."""
    b = hn.shape[0]
    n, d = args.num_heads, args.head_dim
    qkv = jax.nn.silu(hn @ lp["wqkv"]).reshape(b, 1, n, 3 * d)
    q = qkv[:, 0, :, :d].astype(jnp.float32)             # (B, h, d)
    k = qkv[:, 0, :, d : 2 * d].astype(jnp.float32)
    v = qkv[:, 0, :, 2 * d :].astype(jnp.float32)
    ratio = jnp.exp(-slope)[None, :, None, None]
    state = ratio * state + jnp.einsum("bhd,bhe->bhde", k, v)
    out = jnp.einsum("bhd,bhde->bhe", q, state)[:, :, None, :]     # (B,h,1,d)
    out = out.transpose(0, 2, 1, 3).reshape(b, 1, n * d)
    # _finish_lightning expects (B, h, T, d); rebuild that layout
    return _finish_lightning(
        lp, hn, out.reshape(b, 1, n, d).transpose(0, 2, 1, 3)), state


def _finish_lightning(lp, hn, out_heads):
    """(B, h, T, d) attention output -> norm, sigmoid gate, out projection."""
    b, n, t, d = out_heads.shape
    out = out_heads.transpose(0, 2, 1, 3).reshape(b, t, n * d).astype(hn.dtype)
    out = rms_norm(out, lp["attn_norm"], 1e-6)
    gate = jax.nn.sigmoid(hn @ lp["w_gate"])
    return (gate * out) @ lp["out_proj"]


def _full_attn(lp, hn, cos, sin, mask, k_cache, v_cache, positions, bucket, args):
    b, t, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    v = (hn @ lp["wv"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    q, k = rope_ops.apply_rotary(q, k, cos, sin)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, args.q_size)
    return attn @ lp["wo"], k_cache, v_cache


def _moe(lp, hn, args):
    """Mixtral-style sparse MoE: softmax over all experts, top-k, renormalize."""
    b, t, hdim = hn.shape
    x = hn.reshape(b * t, hdim)
    logits = x.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, args.experts_per_tok)
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    gates = jnp.einsum("nk,nke->ne", top_vals,
                       jax.nn.one_hot(top_idx, args.num_experts,
                                      dtype=jnp.float32))
    inter = (jax.nn.silu(jnp.einsum("nh,ehi->eni", x, lp["moe_wg"]))
             * jnp.einsum("nh,ehi->eni", x, lp["moe_wu"]))
    per_expert = jnp.einsum("eni,eih->enh", inter, lp["moe_wd"])
    out = jnp.einsum("enh,ne->nh", per_expert, gates.astype(per_expert.dtype))
    return out.reshape(b, t, hdim).astype(hn.dtype)


def _forward(params, args: MiniMaxArchArgs, h, cos, sin, mask, cache, positions,
             bucket, last_token_idx):
    ks, vs, lins = [], [], []
    ai = li = 0
    for idx, kind in enumerate(args.layer_kinds):
        lp = params["layers"][idx]
        # MiniMax norms the residual STREAM itself (the normed value carries)
        h = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        resid = h
        if kind == "full":
            out, kc, vc = _full_attn(lp, h, cos, sin, mask, cache["k"][ai],
                                     cache["v"][ai], positions, bucket, args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        else:
            slope = jnp.asarray(_slope_rate(args.num_heads, idx,
                                            args.num_layers))
            if positions is None:
                out, state = _lightning_prefill(lp, h, args, last_token_idx,
                                                slope)
            else:
                out, state = _lightning_decode(lp, h, args,
                                               cache["linear"][li], slope)
            lins.append(state)
            li += 1
        h = resid * args.attn_alpha + out * args.attn_beta
        h = rms_norm(h, lp["ln2"], args.rms_norm_eps)
        resid = h
        h = resid * args.mlp_alpha + _moe(lp, h, args) * args.mlp_beta
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs),
                 "linear": jnp.stack(lins)}
    return h, out_cache


def prefill_forward(params, args: MiniMaxArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["lm_head"]).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: MiniMaxArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("MiniMax decode is single-token only (one linear "
                         "state per row)")
    h = jnp.take(params["embed"], input_ids, axis=0)
    pos_grid = position_ids[:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= pos_grid[:, None, :, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache,
                            position_ids, decode_bucket, None)
    logits = (h @ params["lm_head"]).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class MiniMaxInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size", "layer_types",
                           "num_local_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 1000000.0), ("rms_norm_eps", 1e-5),
                              ("block_size", 256),
                              ("full_attn_alpha_factor", 1.0),
                              ("full_attn_beta_factor", 1.0),
                              ("mlp_alpha_factor", 1.0),
                              ("mlp_beta_factor", 1.0),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class MiniMaxForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config,
                                  "MiniMax (lightning attention)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return MiniMaxInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> MiniMaxArchArgs:
        return MiniMaxArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            layer_kinds=tuple("full" if t == "full_attention" else "linear"
                              for t in config.layer_types),
            block_size=int(config.block_size),
            num_experts=int(config.num_local_experts),
            experts_per_tok=int(config.num_experts_per_tok),
            attn_alpha=float(config.full_attn_alpha_factor),
            attn_beta=float(config.full_attn_beta_factor),
            mlp_alpha=float(config.mlp_alpha_factor),
            mlp_beta=float(config.mlp_beta_factor),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # rope applies only to the FULL attention layers' rotary half
        rd = getattr(config, "rotary_dim", None) or config.head_dim
        return rope_ops.default_inv_freq(rd, float(config.rope_theta))

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: MiniMaxArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        n_full = sum(1 for k in a.layer_kinds if k == "full")
        n_lin = len(a.layer_kinds) - n_full
        self.kv_cache = {
            "k": jnp.zeros((max(n_full, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((max(n_full, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "linear": jnp.zeros((max(n_lin, 1), b, a.num_heads,
                                 a.head_dim, a.head_dim), jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        params = jax.tree.map(_put, host_params)
        params["rope_inv_freq"] = jax.device_put(
            np.asarray(host_params["rope_inv_freq"], np.float32))
        self.params = params
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        E = config.num_local_experts
        layers = []
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            lp: Dict[str, np.ndarray] = {
                "ln1": get(p + "input_layernorm.weight"),
                "ln2": get(p + "post_attention_layernorm.weight"),
            }
            if config.layer_types[i] == "full_attention":
                lp["wq"] = lin_t(p + "self_attn.q_proj.weight")
                lp["wk"] = lin_t(p + "self_attn.k_proj.weight")
                lp["wv"] = lin_t(p + "self_attn.v_proj.weight")
                lp["wo"] = lin_t(p + "self_attn.o_proj.weight")
            else:
                lp["wqkv"] = lin_t(p + "self_attn.qkv_proj.weight")
                lp["attn_norm"] = get(p + "self_attn.norm.weight")
                lp["w_gate"] = lin_t(p + "self_attn.output_gate.weight")
                lp["out_proj"] = lin_t(p + "self_attn.out_proj.weight")
            m = p + "block_sparse_moe."
            lp["router"] = lin_t(m + "gate.weight")
            lp["moe_wg"] = np.stack(
                [lin_t(m + f"experts.{e}.w1.weight") for e in range(E)])
            lp["moe_wu"] = np.stack(
                [lin_t(m + f"experts.{e}.w3.weight") for e in range(E)])
            lp["moe_wd"] = np.stack(
                [lin_t(m + f"experts.{e}.w2.weight") for e in range(E)])
            layers.append(lp)
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": layers,
            "final_norm": get("model.norm.weight"),
            "lm_head": lin_t("lm_head.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
