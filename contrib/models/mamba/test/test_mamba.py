"""mamba parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/mamba/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_mamba_parity():
    """Pure selective-SSM family (no attention, no KV cache): associative-scan
    prefill + single-step recurrence decode must match HF's per-token loop."""
    from transformers import MambaConfig, MambaForCausalLM as HFMamba

    from contrib.models.mamba.src.modeling_mamba import MambaForCausalLM

    cfg = MambaConfig(vocab_size=256, hidden_size=64, state_size=8,
                      num_hidden_layers=2, conv_kernel=4, expand=2,
                      time_step_rank=8, use_bias=False, use_conv_bias=True,
                      pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFMamba(cfg).eval()
    _run_parity(MambaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
