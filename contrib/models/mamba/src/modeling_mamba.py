"""Mamba v1 (state-space) on the TPU framework (contrib port).

A pure selective-SSM family — no attention, no KV cache: each layer's state is
a (B, d_inner, d_state) fp32 SSM state plus a (B, conv_kernel, d_inner)
causal-conv tail. TPU redesign:

- **Prefill runs the selective scan as `jax.lax.associative_scan`**: the
  recurrence h_t = exp(ΔA)⊙h_{t-1} + ΔB x_t is diagonal, hence associative in
  (a, b) — log-depth on the VPU instead of the HF reference's per-token Python
  loop. (The scan materializes (B, L, d_inner, d_state) discretized tensors;
  production long-context prefill would chunk the sequence — correctness-first
  here.)
- Right-padded prefill freezes each row's state at its true length (a=1, b=0
  on padding) so decode resumes exactly; the conv tail gathers the last
  conv_kernel real inputs.
- Decode is one fused step: conv-tail dot + a single recurrence update.

≈ reference mamba-family contribs (`contrib/models/Falcon-H1-*/`,
`state-spaces/mamba-*`); math follows HF `MambaMixer.slow_forward`.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class MambaArchArgs(ModelArchArgs):
    d_inner: int = 0
    d_state: int = 16
    d_conv: int = 4
    dt_rank: int = 0
    # falcon-mamba: weightless RMSNorm over the dt/B/C splits of x_proj
    # (HF `FalconMambaMixer.rms_forward`); None = plain mamba
    mixer_rms_eps: Optional[float] = None


def _ssm_params(lp, x, args):
    """x (B, T, I) post-conv activations -> (dA, dBu, C) for the recurrence.
    dA/dBu (B, T, I, S) fp32; C (B, T, S) fp32."""
    proj = x @ lp["x_proj"]                                  # (B, T, R + 2S)
    r, s = args.dt_rank, args.d_state
    dt, b_mat, c_mat = proj[..., :r], proj[..., r : r + s], proj[..., r + s :]
    if args.mixer_rms_eps is not None:
        def _rms(v):
            v32 = v.astype(jnp.float32)
            var = jnp.mean(jnp.square(v32), axis=-1, keepdims=True)
            return (v32 * jax.lax.rsqrt(var + args.mixer_rms_eps)).astype(v.dtype)
        dt, b_mat, c_mat = _rms(dt), _rms(b_mat), _rms(c_mat)
    delta = jax.nn.softplus(
        (dt @ lp["dt_proj"] + lp["dt_bias"]).astype(jnp.float32))   # (B, T, I)
    a = -jnp.exp(lp["a_log"].astype(jnp.float32))            # (I, S)
    d_a = jnp.exp(delta[..., None] * a[None, None])          # (B, T, I, S)
    d_bu = (delta[..., None] * b_mat.astype(jnp.float32)[:, :, None, :]
            * x.astype(jnp.float32)[..., None])              # (B, T, I, S)
    return d_a, d_bu, c_mat.astype(jnp.float32)


def _mixer_prefill(lp, hn, last_token_idx, args):
    """Full-sequence mamba mixer; returns (out (B, T, H), conv_state, ssm_state)."""
    w = args.d_conv
    proj = hn @ lp["in_proj"]                                # (B, T, 2I)
    x, z = proj[..., : args.d_inner], proj[..., args.d_inner :]

    t = x.shape[1]
    # conv tail for decode: the last W real inputs per row (zeros if shorter)
    idx = last_token_idx[:, None] + 1 - w + jnp.arange(w)[None, :]
    gathered = jnp.take_along_axis(x, jnp.clip(idx, 0, t - 1)[:, :, None], axis=1)
    conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)

    xp = jnp.pad(x, ((0, 0), (w - 1, 0), (0, 0)))
    xc = sum(xp[:, j : j + t, :] * lp["conv_w"][j][None, None, :]
             for j in range(w)) + lp["conv_b"][None, None, :]
    xc = jax.nn.silu(xc)

    d_a, d_bu, c_mat = _ssm_params(lp, xc, args)
    valid = (jnp.arange(t)[None, :] <= last_token_idx[:, None])[:, :, None, None]
    # freeze padded positions so the carried state is the last real token's
    d_a = jnp.where(valid, d_a, 1.0)
    d_bu = jnp.where(valid, d_bu, 0.0)

    def comb(l, r):
        return (r[0] * l[0], r[0] * l[1] + r[1])

    _, h_seq = jax.lax.associative_scan(comb, (d_a, d_bu), axis=1)  # (B,T,I,S)
    ssm_state = jnp.take_along_axis(
        h_seq, last_token_idx[:, None, None, None], axis=1)[:, 0]   # (B, I, S)

    y = jnp.einsum("btis,bts->bti", h_seq, c_mat)            # (B, T, I) fp32
    y = y + xc.astype(jnp.float32) * lp["d_skip"].astype(jnp.float32)[None, None]
    y = (y.astype(hn.dtype)) * jax.nn.silu(z)
    return y @ lp["out_proj"], conv_state.astype(hn.dtype), ssm_state


def _mixer_decode(lp, hn, conv_state, ssm_state, args):
    """One-token mamba step. hn (B, 1, H); conv_state (B, W, I) holds the last W
    raw inputs; ssm_state (B, I, S) fp32."""
    proj = hn @ lp["in_proj"]
    x, z = proj[..., : args.d_inner], proj[..., args.d_inner :]
    x0 = x[:, 0]                                             # (B, I)
    state = jnp.concatenate([conv_state[:, 1:], x0[:, None, :]], axis=1)
    xc = jnp.sum(state * lp["conv_w"][None, :, :], axis=1) + lp["conv_b"]
    xc = jax.nn.silu(xc)[:, None, :]                         # (B, 1, I)

    d_a, d_bu, c_mat = _ssm_params(lp, xc, args)
    h = d_a[:, 0] * ssm_state + d_bu[:, 0]                   # (B, I, S)
    y = jnp.einsum("bis,bs->bi", h, c_mat[:, 0])
    y = y + xc[:, 0].astype(jnp.float32) * lp["d_skip"].astype(jnp.float32)[None]
    y = (y.astype(hn.dtype)[:, None, :]) * jax.nn.silu(z)
    return y @ lp["out_proj"], state.astype(conv_state.dtype), h


def _forward(params, args: MambaArchArgs, h, cache, positions, last_token_idx):
    convs, ssms = [], []
    for li in range(args.num_layers):
        lp = jax.tree.map(lambda p: p[li], params["layers"])
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        if positions is None:
            out, conv_state, ssm_state = _mixer_prefill(lp, hn, last_token_idx,
                                                        args)
        else:
            out, conv_state, ssm_state = _mixer_decode(
                lp, hn, cache["conv"][li], cache["ssm"][li], args)
        convs.append(conv_state)
        ssms.append(ssm_state)
        h = h + out
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    return h, {"conv": jnp.stack(convs), "ssm": jnp.stack(ssms)}


def prefill_forward(params, args: MambaArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    h, out_cache = _forward(params, args, h, cache, None, last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h_last @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: MambaArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Mamba decode is single-token only (one SSM state "
                         "per row)")
    h = jnp.take(params["embed"], input_ids, axis=0)
    h, out_cache = _forward(params, args, h, cache, position_ids, None)
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class MambaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers", "vocab_size",
                           "state_size", "conv_kernel")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5),
                              ("use_bias", False), ("use_conv_bias", True),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "intermediate_size") or not self.intermediate_size:
            self.intermediate_size = 2 * self.hidden_size
        if not hasattr(self, "time_step_rank") or self.time_step_rank in (
                None, "auto"):
            import math

            self.time_step_rank = math.ceil(self.hidden_size / 16)
        if self.use_bias:
            raise ValueError("biased in/out projections are not ported yet")


class MambaForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "Mamba (selective SSM)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return MambaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> MambaArchArgs:
        return MambaArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=1, num_kv_heads=1,
            head_dim=config.hidden_size,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_epsilon,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
            d_inner=int(config.intermediate_size),
            d_state=int(config.state_size),
            d_conv=int(config.conv_kernel),
            dt_rank=int(config.time_step_rank),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return np.zeros((1,), np.float32)        # no positional encoding at all

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: MambaArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "conv": jnp.zeros((a.num_layers, b, a.d_conv, a.d_inner), dt),
            "ssm": jnp.zeros((a.num_layers, b, a.d_inner, a.d_state),
                             jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias"}   # recurrence stays fp32

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers: Dict[str, list] = {k: [] for k in
                                   ("ln1", "in_proj", "conv_w", "conv_b",
                                    "x_proj", "dt_proj", "dt_bias", "a_log",
                                    "d_skip", "out_proj")}
        for i in range(config.num_hidden_layers):
            p = f"backbone.layers.{i}."
            mx = p + "mixer."
            layers["ln1"].append(get(p + "norm.weight"))
            layers["in_proj"].append(lin_t(mx + "in_proj.weight"))
            # HF conv (I, 1, W): tap j multiplies x[t - (W-1) + j]
            layers["conv_w"].append(np.ascontiguousarray(
                get(mx + "conv1d.weight")[:, 0, :].T))
            layers["conv_b"].append(get(mx + "conv1d.bias"))
            layers["x_proj"].append(lin_t(mx + "x_proj.weight"))
            layers["dt_proj"].append(lin_t(mx + "dt_proj.weight"))
            layers["dt_bias"].append(get(mx + "dt_proj.bias"))
            layers["a_log"].append(get(mx + "A_log"))
            layers["d_skip"].append(get(mx + "D"))
            layers["out_proj"].append(lin_t(mx + "out_proj.weight"))
        out = {
            "embed": get("backbone.embeddings.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("backbone.norm_f.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not getattr(config, "tie_word_embeddings", True):
            out["lm_head"] = lin_t("lm_head.weight")
        return out
