"""Cohere2 / Command-R7B on the TPU framework (contrib port).

≈ reference `contrib/models/c4ai-command-r7b-12-2024/`. Command-R7B combines
the Cohere block (single-LayerNorm parallel residual, interleaved rotary,
logit_scale, tied embeddings) with a 3:1 sliding/full layer pattern where the
FULL-attention layers use NO positional encoding (NoPE). Mapping: the shared
layer-pattern machinery (rolling window caches for sliding layers) with the
full-layer rope table set to ZERO inverse frequencies — cos=1/sin=0 makes the
rotation the identity, i.e. NoPE — and the sliding layers on the real rope
table via the local-rope hook.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Cohere2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size", "layer_types")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("layer_norm_eps", 1e-5),
                              ("logit_scale", 1.0), ("sliding_window", 4096)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads

    def layer_pattern(self):
        return tuple("sliding" if t == "sliding_attention" else "full"
                     for t in self.layer_types)


class Cohere2ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Cohere2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            norm_type="layer",
            parallel_residual=True,
            shared_ln=True,
            rope_interleaved=True,
            sliding_window=int(config.sliding_window),
            layer_pattern=config.layer_pattern(),
            local_rope_theta=float(config.rope_theta),   # sliding layers' table;
            #                                              full layers' is zeroed

            logits_scale=float(config.logit_scale),
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # FULL layers are NoPE: a zero inv-freq table makes rotary the identity
        rd = config.head_dim
        return np.zeros((rd // 2,), np.float32)

    @classmethod
    def local_inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            ln = get(p + "input_layernorm.weight")
            layers["ln1"].append(ln)
            layers["ln2"].append(np.ones_like(ln))   # unused under shared_ln
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
            "rope_inv_freq_local": cls.local_inv_freq_from_config(config),
        }
