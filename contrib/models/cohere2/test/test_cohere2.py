"""cohere2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/cohere2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_cohere2_parity():
    """Command-R7B: cohere parallel-residual block + 3:1 sliding/full pattern
    where full layers are NoPE (zero-inv-freq rope table = identity rotation)."""
    from transformers import Cohere2Config, Cohere2ForCausalLM as HFCohere2

    from contrib.models.cohere2.src.modeling_cohere2 import Cohere2ForCausalLM

    cfg = Cohere2Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=4, num_attention_heads=4,
                        num_key_value_heads=2, logit_scale=0.25,
                        sliding_window=16,
                        layer_types=["sliding_attention", "sliding_attention",
                                     "sliding_attention", "full_attention"],
                        pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFCohere2(cfg).eval()
    _run_parity(Cohere2ForCausalLM, hf, cfg)
