"""Mamba-2 (SSD / state-space duality) on the TPU framework (contrib port).

The multi-head successor of mamba1: per-head SCALAR decay a_t = e^{Δ_t A_h}
over a (B, heads, head_dim, state) fp32 SSM state, grouped B/C projections,
joint x|B|C causal conv, per-head Δ with softplus + clamp, and a GATED output
RMSNorm (norm(y · silu(z))). TPU redesign mirrors contrib/models/mamba:
associative-scan prefill over the diagonal recurrence (the scalar per-head
decay broadcasts over (head_dim, state)), right padding frozen at each row's
true length, fused single-step decode. Math follows HF
`Mamba2Mixer.torch_forward`.
"""

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class Mamba2ArchArgs(ModelArchArgs):
    d_inner: int = 0
    d_state: int = 128
    d_conv: int = 4
    ssd_heads: int = 128
    ssd_head_dim: int = 64
    n_groups: int = 8
    dt_min: float = 0.0
    dt_max: float = float("inf")
    # zamba2: grouped gated-norm variance (HF Zamba2RMSNormGated group_size);
    # 1 = HF Mamba2's ungrouped MambaRMSNormGated
    gate_norm_groups: int = 1
    gate_norm_eps: Optional[float] = None

    @property
    def conv_dim(self) -> int:
        return self.d_inner + 2 * self.n_groups * self.d_state


def _expand_groups(x, n_heads, n_groups):
    """(B, T, groups*state) -> (B, T, heads, state) (group-to-head repeat)."""
    b, t, _ = x.shape
    x = x.reshape(b, t, n_groups, -1)
    return jnp.repeat(x, n_heads // n_groups, axis=2)


def _ssm_terms(lp, xc, dt_raw, args):
    """Post-conv split + discretization: returns (a, b_term, c, x_heads), with
    a (B, T, nh, 1, 1) fp32 scalar decays and b_term = Δ·(B ⊗ x) (B, T, nh, hd, s)."""
    bsz, t, _ = xc.shape
    nh, hd, s = args.ssd_heads, args.ssd_head_dim, args.d_state
    x = xc[..., : args.d_inner].reshape(bsz, t, nh, hd)
    b_mat = _expand_groups(
        xc[..., args.d_inner : args.d_inner + args.n_groups * s],
        nh, args.n_groups).astype(jnp.float32)               # (B, T, nh, s)
    c_mat = _expand_groups(
        xc[..., args.d_inner + args.n_groups * s :],
        nh, args.n_groups).astype(jnp.float32)
    dt = jax.nn.softplus(dt_raw.astype(jnp.float32)
                         + lp["dt_bias"].astype(jnp.float32))       # (B, T, nh)
    dt = jnp.clip(dt, args.dt_min, args.dt_max)
    a_h = -jnp.exp(lp["a_log"].astype(jnp.float32))          # (nh,)
    a = jnp.exp(dt * a_h[None, None, :])[..., None, None]    # (B, T, nh, 1, 1)
    b_term = (dt[..., None, None] * b_mat[:, :, :, None, :]
              * x.astype(jnp.float32)[..., None])            # (B, T, nh, hd, s)
    return a, b_term, c_mat, x


def _conv_prefill(lp, xbc, last_token_idx, args):
    """Joint causal conv over x|B|C; returns (activated (B,T,conv_dim), tail)."""
    w = args.d_conv
    t = xbc.shape[1]
    idx = last_token_idx[:, None] + 1 - w + jnp.arange(w)[None, :]
    gathered = jnp.take_along_axis(xbc, jnp.clip(idx, 0, t - 1)[:, :, None],
                                   axis=1)
    conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)
    xp = jnp.pad(xbc, ((0, 0), (w - 1, 0), (0, 0)))
    xc = sum(xp[:, j : j + t, :] * lp["conv_w"][j][None, None, :]
             for j in range(w)) + lp["conv_b"][None, None, :]
    return jax.nn.silu(xc), conv_state


def _mixer_prefill(lp, hn, last_token_idx, args):
    t = hn.shape[1]
    proj = hn @ lp["in_proj"]
    z = proj[..., : args.d_inner]
    xbc = proj[..., args.d_inner : args.d_inner + args.conv_dim]
    dt_raw = proj[..., args.d_inner + args.conv_dim :]       # (B, T, nh)

    xc, conv_state = _conv_prefill(lp, xbc, last_token_idx, args)
    a, b_term, c_mat, x = _ssm_terms(lp, xc, dt_raw, args)

    valid = (jnp.arange(t)[None, :] <= last_token_idx[:, None])[..., None, None,
                                                                None]
    a = jnp.where(valid, a, 1.0)
    b_term = jnp.where(valid, b_term, 0.0)

    def comb(l, r):
        return (r[0] * l[0], r[0] * l[1] + r[1])

    _, h_seq = jax.lax.associative_scan(comb, (a, b_term), axis=1)
    ssm_state = jnp.take_along_axis(
        h_seq, last_token_idx[:, None, None, None, None], axis=1)[:, 0]

    y = jnp.einsum("bthds,bths->bthd", h_seq, c_mat)         # fp32
    y = y + x.astype(jnp.float32) * lp["d_skip"].astype(jnp.float32)[None, None,
                                                                     :, None]
    y = y.reshape(hn.shape[0], t, args.d_inner)
    y = _gated_norm(lp, y, z, args)
    return y @ lp["out_proj"], conv_state.astype(hn.dtype), ssm_state


def _mixer_decode(lp, hn, conv_state, ssm_state, args):
    b = hn.shape[0]
    proj = hn @ lp["in_proj"]
    z = proj[..., : args.d_inner]
    xbc = proj[..., args.d_inner : args.d_inner + args.conv_dim][:, 0]
    dt_raw = proj[..., args.d_inner + args.conv_dim :]

    state = jnp.concatenate([conv_state[:, 1:], xbc[:, None, :]], axis=1)
    xc = jnp.sum(state * lp["conv_w"][None, :, :], axis=1) + lp["conv_b"]
    xc = jax.nn.silu(xc)[:, None, :]

    a, b_term, c_mat, x = _ssm_terms(lp, xc, dt_raw, args)
    h = a[:, 0] * ssm_state + b_term[:, 0]                   # (B, nh, hd, s)
    y = jnp.einsum("bhds,bhs->bhd", h, c_mat[:, 0])
    y = y + x[:, 0].astype(jnp.float32) * lp["d_skip"].astype(
        jnp.float32)[None, :, None]
    y = y.reshape(b, 1, args.d_inner)
    y = _gated_norm(lp, y, z, args)
    return y @ lp["out_proj"], state.astype(conv_state.dtype), h


def _gated_norm(lp, y, z, args):
    """Gated RMSNorm: norm(y * silu(z)) * w (HF MambaRMSNormGated); variance
    per ``gate_norm_groups`` groups (Zamba2RMSNormGated) when > 1."""
    eps = (args.gate_norm_eps if args.gate_norm_eps is not None
           else args.rms_norm_eps)
    y = y * jax.nn.silu(z.astype(jnp.float32))
    g = args.gate_norm_groups
    if g == 1:
        return rms_norm(y, lp["gate_norm"], eps).astype(lp["out_proj"].dtype)
    *lead, dim = y.shape
    yg = y.reshape(*lead, g, dim // g)
    var = jnp.mean(jnp.square(yg), axis=-1, keepdims=True)
    y = (yg * jax.lax.rsqrt(var + eps)).reshape(*lead, dim)
    return (lp["gate_norm"].astype(jnp.float32) * y).astype(
        lp["out_proj"].dtype)


def _forward(params, args: Mamba2ArchArgs, h, cache, positions, last_token_idx):
    convs, ssms = [], []
    for li in range(args.num_layers):
        lp = jax.tree.map(lambda p: p[li], params["layers"])
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        if positions is None:
            out, conv_state, ssm_state = _mixer_prefill(lp, hn, last_token_idx,
                                                        args)
        else:
            out, conv_state, ssm_state = _mixer_decode(
                lp, hn, cache["conv"][li], cache["ssm"][li], args)
        convs.append(conv_state)
        ssms.append(ssm_state)
        h = h + out
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    return h, {"conv": jnp.stack(convs), "ssm": jnp.stack(ssms)}


def prefill_forward(params, args: Mamba2ArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    h, out_cache = _forward(params, args, h, cache, None, last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h_last @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: Mamba2ArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Mamba2 decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    h, out_cache = _forward(params, args, h, cache, position_ids, None)
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class Mamba2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers", "vocab_size",
                           "state_size", "conv_kernel", "num_heads", "head_dim")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5), ("n_groups", 1),
                              ("use_bias", False), ("use_conv_bias", True),
                              ("expand", 2), ("time_step_limit", (0.0, 1e9)),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "intermediate_size") or not self.intermediate_size:
            self.intermediate_size = int(self.expand * self.hidden_size)
        if self.use_bias:
            raise ValueError("biased in/out projections are not ported yet")
        if self.num_heads * self.head_dim != self.intermediate_size:
            raise ValueError("num_heads * head_dim must equal intermediate_size")


class Mamba2ForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "Mamba2 (SSD)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return Mamba2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> Mamba2ArchArgs:
        lim = tuple(config.time_step_limit)
        return Mamba2ArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=1, num_kv_heads=1,
            head_dim=config.hidden_size,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_epsilon,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            d_inner=int(config.intermediate_size),
            d_state=int(config.state_size),
            d_conv=int(config.conv_kernel),
            ssd_heads=int(config.num_heads),
            ssd_head_dim=int(config.head_dim),
            n_groups=int(config.n_groups),
            dt_min=float(lim[0]),
            dt_max=float(min(lim[1], 1e9)),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return np.zeros((1,), np.float32)

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: Mamba2ArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "conv": jnp.zeros((a.num_layers, b, a.d_conv, a.conv_dim), dt),
            "ssm": jnp.zeros((a.num_layers, b, a.ssd_heads, a.ssd_head_dim,
                              a.d_state), jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias"}

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers: Dict[str, list] = {k: [] for k in
                                   ("ln1", "in_proj", "conv_w", "conv_b",
                                    "dt_bias", "a_log", "d_skip", "gate_norm",
                                    "out_proj")}
        for i in range(config.num_hidden_layers):
            p = f"backbone.layers.{i}."
            mx = p + "mixer."
            layers["ln1"].append(get(p + "norm.weight"))
            layers["in_proj"].append(lin_t(mx + "in_proj.weight"))
            layers["conv_w"].append(np.ascontiguousarray(
                get(mx + "conv1d.weight")[:, 0, :].T))
            layers["conv_b"].append(get(mx + "conv1d.bias"))
            layers["dt_bias"].append(get(mx + "dt_bias"))
            layers["a_log"].append(get(mx + "A_log"))
            layers["d_skip"].append(get(mx + "D"))
            layers["gate_norm"].append(get(mx + "norm.weight"))
            layers["out_proj"].append(lin_t(mx + "out_proj.weight"))
        out = {
            "embed": get("backbone.embeddings.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("backbone.norm_f.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
