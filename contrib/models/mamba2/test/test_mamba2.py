"""mamba2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/mamba2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_mamba2_parity():
    """Mamba-2 / SSD: per-head scalar-decay multi-head SSM with grouped B/C,
    joint x|B|C conv, and gated output RMSNorm — associative-scan prefill."""
    from transformers import Mamba2Config, Mamba2ForCausalLM as HFMamba2

    from contrib.models.mamba2.src.modeling_mamba2 import Mamba2ForCausalLM

    cfg = Mamba2Config(vocab_size=256, hidden_size=32, state_size=8,
                       num_hidden_layers=2, conv_kernel=4, expand=2,
                       num_heads=4, head_dim=16, n_groups=2,
                       use_bias=False, use_conv_bias=True,
                       pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFMamba2(cfg).eval()
    _run_parity(Mamba2ForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)


def test_mamba2_untied_lm_head():
    from transformers import Mamba2Config, Mamba2ForCausalLM as HFMamba2

    from contrib.models.mamba2.src.modeling_mamba2 import Mamba2ForCausalLM

    cfg = Mamba2Config(vocab_size=256, hidden_size=32, state_size=8,
                       num_hidden_layers=2, conv_kernel=4, expand=2,
                       num_heads=4, head_dim=16, n_groups=2,
                       use_bias=False, use_conv_bias=True,
                       pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(3)
    hf = HFMamba2(cfg).eval()
    _run_parity(Mamba2ForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
