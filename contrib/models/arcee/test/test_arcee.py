"""arcee parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/arcee/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_arcee_parity():
    """Arcee/AFM: llama-geometry GQA with a ReLU^2 PLAIN MLP (up->relu^2->down,
    no gate) and YaRN rope scaling (exercised at factor 4)."""
    from transformers import ArceeConfig, ArceeForCausalLM as HFArcee

    from contrib.models.arcee.src.modeling_arcee import ArceeForCausalLM

    cfg = ArceeConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16,
                      rope_scaling={"rope_type": "yarn", "factor": 4.0,
                                    "original_max_position_embeddings": 32,
                                    "beta_fast": 32.0, "beta_slow": 1.0},
                      max_position_embeddings=128,
                      pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFArcee(cfg).eval()
    _run_parity(ArceeForCausalLM, hf, cfg)
