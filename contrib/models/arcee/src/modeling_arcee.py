"""Arcee / AFM-4.5B on the TPU framework (contrib port).

Llama-geometry GQA decoder whose MLP is a ReLU-squared *plain* stack
(up_proj -> relu(x)^2 -> down_proj, no gate), with YaRN rope scaling for the
65k context window. ≈ reference `contrib/models/AFM-4.5B-Base/src/modeling_afm.py`
(arch summary in its README: YaRN factor 20, relu2, separate q/k/v fused at
conversion). Maps onto the shared core via mlp_kind="plain" + activation="relu2"
and `rope_ops.inv_freq_from_hf_config` (yarn NTK-by-parts).
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class ArceeInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("attention_bias", False), ("mlp_bias", False),
                              ("rope_scaling", None),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "num_key_value_heads") \
                or self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class ArceeForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return ArceeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation="relu2",
            mlp_kind="plain",
            mlp_bias=bool(config.mlp_bias),
            attention_bias=bool(config.attention_bias),
            rope_attention_scaling=rope_ops.attention_scaling_from_hf_config(
                getattr(config, "rope_scaling", None)),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.inv_freq_from_hf_config(
            config.head_dim, float(config.rope_theta),
            getattr(config, "rope_scaling", None))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ["ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wd"]
        if config.attention_bias:
            keys += ["bq", "bk", "bv"]
        if config.mlp_bias:
            keys += ["bg", "bd"]
        layers = {k: [] for k in keys}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            if config.attention_bias:
                layers["bq"].append(get(p + "self_attn.q_proj.bias"))
                layers["bk"].append(get(p + "self_attn.k_proj.bias"))
                layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            # plain MLP: fc1 (wg) -> relu^2 -> fc2 (wd)
            layers["wg"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
            if config.mlp_bias:
                layers["bg"].append(get(p + "mlp.up_proj.bias"))
                layers["bd"].append(get(p + "mlp.down_proj.bias"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
