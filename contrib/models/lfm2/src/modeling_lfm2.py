"""LFM2 (Liquid) on the TPU framework (contrib port).

≈ reference `contrib/models/lfm2-2.6b/`. A conv/attention hybrid: most layers
are gated short-convolution blocks (in_proj -> B·x through a depthwise causal
conv of width L_cache, gated by C, out_proj) whose per-layer state is the last
L_cache gated inputs — not a KV cache; the sparse full-attention layers use
per-head RMSNorm on q AND k (qk-norm). The hybrid cache pytree carries a
(L_conv, B, L_cache, H) conv tail next to the attention layers' stacked KV.
Prefill computes the causal conv as a width-static sum of shifted slices (the
kernel is tiny); right padding gathers each row's last L_cache real inputs so
decode resumes exactly at the true length.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class Lfm2ArchArgs(ModelArchArgs):
    conv_l_cache: int = 3
    block_types: Tuple[str, ...] = ()    # per-layer "conv" | "full_attention"


def _conv_block_prefill(lp, hn, last_token_idx, args):
    """Gated short conv over the full sequence; returns (out, conv_state)."""
    L = args.conv_l_cache
    bcx = hn @ lp["w_in"]                                  # (B, S, 3H)
    H = hn.shape[-1]
    b_g, c_g, x = bcx[..., :H], bcx[..., H : 2 * H], bcx[..., 2 * H :]
    bx = b_g * x

    s = x.shape[1]
    # decode tail: the last L real gated inputs per row (zeros if shorter)
    idx = last_token_idx[:, None] + 1 - L + jnp.arange(L)[None, :]
    gathered = jnp.take_along_axis(bx, jnp.clip(idx, 0, s - 1)[:, :, None], axis=1)
    conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)

    xp = jnp.pad(bx, ((0, 0), (L - 1, 0), (0, 0)))
    conv = sum(xp[:, j : j + s, :] * lp["conv_w"][j][None, None, :]
               for j in range(L))
    y = c_g * conv
    return y @ lp["w_out"], conv_state


def _conv_block_decode(lp, hn, conv_state, args):
    """One-token conv step; conv_state (B, L, H) holds the last L gated inputs."""
    bcx = hn @ lp["w_in"]                                  # (B, 1, 3H)
    H = hn.shape[-1]
    b_g, c_g, x = bcx[..., :H], bcx[..., H : 2 * H], bcx[..., 2 * H :]
    bx = (b_g * x)[:, 0]                                   # (B, H)
    state = jnp.concatenate([conv_state[:, 1:], bx[:, None, :]], axis=1)
    conv = jnp.sum(state * lp["conv_w"][None, :, :], axis=1)   # (B, H)
    y = c_g * conv[:, None, :]
    return y @ lp["w_out"], state


def _attn_block(lp, hn, cos, sin, mask, k_cache, v_cache, positions, bucket, args):
    b, s, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, s, args.num_heads, args.head_dim)
    k = (hn @ lp["wk"]).reshape(b, s, args.num_kv_heads, args.head_dim)
    v = (hn @ lp["wv"]).reshape(b, s, args.num_kv_heads, args.head_dim)
    # per-head RMSNorm on q and k (applied before the head transpose, HF order)
    q = rms_norm(q, lp["q_norm"], args.rms_norm_eps).transpose(0, 2, 1, 3)
    k = rms_norm(k, lp["k_norm"], args.rms_norm_eps).transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q, k = rope_ops.apply_rotary(q, k, cos, sin)

    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)

    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, args.q_size)
    return attn @ lp["wo"], k_cache, v_cache


def _mlp(lp, hn):
    return (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]


def _forward(params, args: Lfm2ArchArgs, h, cos, sin, mask, cache, positions,
             decode_bucket, last_token_idx):
    ks, vs, convs = [], [], []
    ai = ci = 0
    for li, kind in enumerate(args.block_types):
        lp = jax.tree.map(lambda p: p[li], params["layers"])
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        if kind == "full_attention":
            out, kc, vc = _attn_block(lp, hn, cos, sin, mask, cache["k"][ai],
                                      cache["v"][ai], positions, decode_bucket,
                                      args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        elif positions is None:
            out, conv_state = _conv_block_prefill(lp, hn, last_token_idx, args)
            convs.append(conv_state)
            ci += 1
        else:
            out, conv_state = _conv_block_decode(lp, hn, cache["conv"][ci], args)
            convs.append(conv_state)
            ci += 1
        h = h + out
        h = h + _mlp_in(lp, h, args)
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs),
                 "conv": jnp.stack(convs)}
    return h, out_cache


def _mlp_in(lp, h, args):
    return _mlp(lp, rms_norm(h, lp["ln2"], args.rms_norm_eps))


def prefill_forward(params, args: Lfm2ArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    s = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(s, s)[None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["embed"].T).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: Lfm2ArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("LFM2 decode is single-token only (the conv state "
                         "carries one tail per row)")
    h = jnp.take(params["embed"], input_ids, axis=0)
    pos_grid = position_ids[:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= pos_grid[:, None, :, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache,
                            position_ids, decode_bucket, None)
    logits = (h @ params["embed"].T).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class Lfm2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size", "layer_types")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 1000000.0), ("norm_eps", 1e-5),
                              ("conv_L_cache", 3), ("conv_bias", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class Lfm2ForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "LFM2 (conv hybrid)")
        if getattr(config, "conv_bias", False):
            raise ValueError("conv_bias=True is not ported yet")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return Lfm2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> Lfm2ArchArgs:
        return Lfm2ArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.norm_eps,
            qk_norm=True,
            tie_word_embeddings=True,
            conv_l_cache=int(config.conv_L_cache),
            block_types=tuple(config.layer_types),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: Lfm2ArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        n_att = sum(1 for k in a.block_types if k == "full_attention")
        n_conv = len(a.block_types) - n_att
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "k": jnp.zeros((max(n_att, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((max(n_att, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((max(n_conv, 1), b, a.conv_l_cache,
                               a.hidden_size), dt),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        params = jax.tree.map(_put, host_params)
        params["rope_inv_freq"] = jax.device_put(
            np.asarray(host_params["rope_inv_freq"], np.float32))
        self.params = params
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        H = config.hidden_size
        hd = config.head_dim
        zeros = {
            "wq": np.zeros((H, config.num_attention_heads * hd), np.float32),
            "wk": np.zeros((H, config.num_key_value_heads * hd), np.float32),
            "wv": np.zeros((H, config.num_key_value_heads * hd), np.float32),
            "wo": np.zeros((config.num_attention_heads * hd, H), np.float32),
            "q_norm": np.zeros((hd,), np.float32),
            "k_norm": np.zeros((hd,), np.float32),
            "w_in": np.zeros((H, 3 * H), np.float32),
            "w_out": np.zeros((H, H), np.float32),
            "conv_w": np.zeros((config.conv_L_cache, H), np.float32),
        }
        layers: Dict[str, list] = {k: [] for k in
                                   list(zeros) + ["ln1", "ln2", "wg", "wu", "wd"]}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "operator_norm.weight"))
            layers["ln2"].append(get(p + "ffn_norm.weight"))
            layers["wg"].append(lin_t(p + "feed_forward.w1.weight"))
            layers["wu"].append(lin_t(p + "feed_forward.w3.weight"))
            layers["wd"].append(lin_t(p + "feed_forward.w2.weight"))
            filled = dict(zeros)
            if config.layer_types[i] == "full_attention":
                filled["wq"] = lin_t(p + "self_attn.q_proj.weight")
                filled["wk"] = lin_t(p + "self_attn.k_proj.weight")
                filled["wv"] = lin_t(p + "self_attn.v_proj.weight")
                filled["wo"] = lin_t(p + "self_attn.out_proj.weight")
                filled["q_norm"] = get(p + "self_attn.q_layernorm.weight")
                filled["k_norm"] = get(p + "self_attn.k_layernorm.weight")
            else:
                filled["w_in"] = lin_t(p + "conv.in_proj.weight")
                filled["w_out"] = lin_t(p + "conv.out_proj.weight")
                # HF conv (H, 1, L): tap j multiplies x[t - (L-1) + j]
                filled["conv_w"] = np.ascontiguousarray(
                    get(p + "conv.conv.weight")[:, 0, :].T)
            for k, v in filled.items():
                layers[k].append(v)
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.embedding_norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
