"""lfm2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/lfm2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_lfm2_parity():
    """LFM2 conv/attention hybrid: gated short-conv state cache + qk-norm
    attention layers in one hybrid cache pytree."""
    from transformers import Lfm2Config, Lfm2ForCausalLM as HFLfm2

    from contrib.models.lfm2.src.modeling_lfm2 import Lfm2ForCausalLM

    cfg = Lfm2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        conv_L_cache=3, conv_bias=False, block_auto_adjust_ff_dim=False,
        layer_types=["conv", "conv", "full_attention", "conv"],
        pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFLfm2(cfg).eval()
    _run_parity(Lfm2ForCausalLM, hf, cfg, atol=1e-3, rtol=1e-3)
