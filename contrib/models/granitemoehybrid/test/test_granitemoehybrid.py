"""granitemoehybrid parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/granitemoehybrid/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_granitemoehybrid_parity():
    """GraniteMoeHybrid (granite-4.0 h-family): bamba-style mamba2/attention
    layers, each ending in topk_softmax MoE + ungated shared expert, with
    granite multipliers and NoPE attention."""
    from transformers import (GraniteMoeHybridConfig,
                              GraniteMoeHybridForCausalLM as HFGmh)

    from contrib.models.granitemoehybrid.src.modeling_granitemoehybrid import (
        GraniteMoeHybridForCausalLM)

    cfg = GraniteMoeHybridConfig(
        vocab_size=256, hidden_size=32, num_hidden_layers=3,
        layers_block_type=["mamba", "attention", "mamba"],
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        shared_intermediate_size=48, num_local_experts=4,
        num_experts_per_tok=2, mamba_n_heads=8, mamba_d_head=8,
        mamba_n_groups=2, mamba_d_state=8, mamba_d_conv=4, mamba_expand=2,
        embedding_multiplier=2.0, attention_multiplier=0.3,
        residual_multiplier=0.8, logits_scaling=1.5,
        position_embedding_type=None, attention_bias=False,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(0)
    hf = HFGmh(cfg).eval()
    _run_parity(GraniteMoeHybridForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
