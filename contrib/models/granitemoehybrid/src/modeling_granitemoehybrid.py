"""GraniteMoeHybrid (IBM granite-4.0 h-family) on the TPU framework
(contrib port).

≈ reference contrib granite family. Bamba's heterogeneous layout (mamba2 SSD
mixer layers OR GQA attention layers, per layers_block_type) combined with
granite's block: every layer ends in the shared ops/moe.py MoE FFN
(topk_softmax routing + ungated dense shared expert, so EP sharding and
quantization ride along), with the granite multiplier family (embedding,
residual, logits_scaling) and attention scaled by the raw
attention_multiplier. Rope only when position_embedding_type == "rope"
(granite-4.0-h ships NoPE → zero inv-freq table, identity rotation). The
mixer and attention come from contrib/models/{mamba2,bamba}.
"""

from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from contrib.models.bamba.src.modeling_bamba import (BambaArchArgs,
                                                     BambaForCausalLM,
                                                     _attn)
from contrib.models.mamba2.src.modeling_mamba2 import (_mixer_decode,
                                                       _mixer_prefill)
from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import causal_mask
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs, moe_block
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class GraniteMoeHybridArchArgs(BambaArchArgs):
    residual_multiplier: float = 1.0
    logits_scale: float = 1.0


def _ffn(lp, hn, args, mesh, rules, decode):
    """Shared-core MoE FFN; shared-expert-only when num_local_experts == 0."""
    if args.moe is not None:
        return moe_block(lp, args, hn, mesh, rules, jax.nn.silu, decode=decode)
    b, t, hdim = hn.shape
    x = hn.reshape(b * t, hdim)
    shared = (jax.nn.silu(x @ lp["shared_wg"]) * (x @ lp["shared_wu"])
              ) @ lp["shared_wd"]
    return shared.reshape(b, t, hdim).astype(hn.dtype)


def _forward(params, args: GraniteMoeHybridArchArgs, h, cos, sin, mask, cache,
             positions, bucket, last_token_idx, mesh, rules):
    ks, vs, convs, ssms = [], [], [], []
    ai = mi = 0
    rm = args.residual_multiplier
    for li, kind in enumerate(args.layer_kinds):
        lp = params["layers"][li]
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        if kind == "attention":
            out, kc, vc = _attn(lp, hn, cos, sin, mask, cache["k"][ai],
                                cache["v"][ai], positions, bucket, args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        elif positions is None:
            out, conv_state, ssm_state = _mixer_prefill(lp, hn, last_token_idx,
                                                        args)
            convs.append(conv_state)
            ssms.append(ssm_state)
            mi += 1
        else:
            out, conv_state, ssm_state = _mixer_decode(
                lp, hn, cache["conv"][mi], cache["ssm"][mi], args)
            convs.append(conv_state)
            ssms.append(ssm_state)
            mi += 1
        h = h + out * rm
        hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
        h = h + _ffn(lp, hn, args, mesh, rules,
                     decode=positions is not None) * rm
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks) if ks else cache["k"],
                 "v": jnp.stack(vs) if vs else cache["v"],
                 "conv": jnp.stack(convs) if convs else cache["conv"],
                 "ssm": jnp.stack(ssms) if ssms else cache["ssm"]}
    return h, out_cache


def prefill_forward(params, args: GraniteMoeHybridArchArgs, input_ids,
                    position_ids, last_token_idx, cache, mesh=None, rules=None,
                    use_flash=False, adapter_ids=None, use_ring=False,
                    return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache, None, None,
                            last_token_idx, mesh, rules)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h_last @ head).astype(jnp.float32) * args.logits_scale
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: GraniteMoeHybridArchArgs, input_ids,
                   position_ids, cache, decode_bucket, mesh=None, rules=None,
                   adapter_ids=None, tree=None, return_hidden=False,
                   **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("GraniteMoeHybrid decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"],
                                        position_ids[:, None])
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= position_ids[:, None, None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache,
                            position_ids, decode_bucket, None, mesh, rules)
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h @ head).astype(jnp.float32) * args.logits_scale
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class GraniteMoeHybridInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "mamba_n_heads", "mamba_d_state")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("mamba_d_conv", 4), ("mamba_expand", 2),
                              ("mamba_n_groups", 1),
                              ("num_local_experts", 0),
                              ("num_experts_per_tok", 0),
                              ("shared_intermediate_size", 0),
                              ("embedding_multiplier", 1.0),
                              ("attention_multiplier", 1.0),
                              ("residual_multiplier", 1.0),
                              ("logits_scaling", 1.0),
                              ("position_embedding_type", None),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                if default is not None or not hasattr(self, attr):
                    setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if not getattr(self, "layers_block_type", None):
            # HF serializes layers_block_type under `layer_types`
            self.layers_block_type = (getattr(self, "layer_types", None)
                                      or ["mamba"] * self.num_hidden_layers)
        if getattr(self, "attention_bias", False):
            raise ValueError("GraniteMoeHybrid attention_bias=True is not "
                             "ported (released checkpoints are bias-free)")


class GraniteMoeHybridForCausalLM(BambaForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config,
                                  "GraniteMoeHybrid (mamba2/attention/MoE)")
        TpuModelForCausalLM.__init__(self, model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return GraniteMoeHybridInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> GraniteMoeHybridArchArgs:
        d_inner = int(config.mamba_expand * config.hidden_size)
        moe = None
        if int(config.num_local_experts):
            moe = MoEArgs(
                num_experts=int(config.num_local_experts),
                experts_per_tok=int(config.num_experts_per_tok),
                router_mode="topk_softmax",
                shared_expert_intermediate_size=int(
                    config.shared_intermediate_size),
                shared_expert_gated=False)
        return GraniteMoeHybridArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            embedding_multiplier=float(config.embedding_multiplier),
            tie_word_embeddings=bool(config.tie_word_embeddings),
            moe=moe,
            d_inner=d_inner,
            d_state=int(config.mamba_d_state),
            d_conv=int(config.mamba_d_conv),
            ssd_heads=int(config.mamba_n_heads),
            ssd_head_dim=int(d_inner // config.mamba_n_heads),
            n_groups=int(config.mamba_n_groups),
            layer_kinds=tuple(config.layers_block_type),
            # full-width rotation; NoPE rides a zero inv-freq table
            rotary_dim=int(config.head_dim),
            attention_scale=float(config.attention_multiplier),
            residual_multiplier=float(config.residual_multiplier),
            logits_scale=1.0 / float(config.logits_scaling),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        if config.position_embedding_type == "rope":
            return rope_ops.default_inv_freq(config.head_dim,
                                             float(config.rope_theta))
        return np.zeros((config.head_dim // 2,), np.float32)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        I, S = config.intermediate_size, config.shared_intermediate_size
        layers = []
        for i, kind in enumerate(config.layers_block_type):
            p = f"model.layers.{i}."
            sm = p + "shared_mlp."
            fused = get(sm + "input_linear.weight")                 # (2S, H)
            lp = {
                "ln1": get(p + "input_layernorm.weight"),
                "ln2": get(p + "post_attention_layernorm.weight"),
                "shared_wg": np.ascontiguousarray(fused[:S, :].T),
                "shared_wu": np.ascontiguousarray(fused[S:, :].T),
                "shared_wd": lin_t(sm + "output_linear.weight"),
            }
            if config.num_local_experts:
                mo = p + "block_sparse_moe."
                ef = get(mo + "input_linear.weight")                # (E, 2I, H)
                lp.update({
                    "router": lin_t(mo + "router.layer.weight"),
                    "wg": np.ascontiguousarray(
                        ef[:, :I, :].transpose(0, 2, 1)),
                    "wu": np.ascontiguousarray(
                        ef[:, I:, :].transpose(0, 2, 1)),
                    "wd": np.ascontiguousarray(
                        get(mo + "output_linear.weight").transpose(0, 2, 1)),
                })
            if kind == "attention":
                lp.update({
                    "wq": lin_t(p + "self_attn.q_proj.weight"),
                    "wk": lin_t(p + "self_attn.k_proj.weight"),
                    "wv": lin_t(p + "self_attn.v_proj.weight"),
                    "wo": lin_t(p + "self_attn.o_proj.weight"),
                })
            else:
                mx = p + "mamba."
                lp.update({
                    "in_proj": lin_t(mx + "in_proj.weight"),
                    "conv_w": np.ascontiguousarray(
                        get(mx + "conv1d.weight")[:, 0, :].T),
                    "conv_b": get(mx + "conv1d.bias"),
                    "dt_bias": get(mx + "dt_bias"),
                    "a_log": get(mx + "A_log"),
                    "d_skip": get(mx + "D"),
                    "gate_norm": get(mx + "norm.weight"),
                    "out_proj": lin_t(mx + "out_proj.weight"),
                })
            layers.append(lp)
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": layers,
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
