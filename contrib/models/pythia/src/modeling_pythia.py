"""Pythia / GPT-NeoX on the TPU framework (contrib port, ≈ reference
`contrib/models/pythia-2.8b/`).

Exercises: partial rotary (rotary_pct), parallel residual, per-head-interleaved
fused query_key_value split, biased LayerNorm, plain gelu MLP, untied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class PythiaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rotary_pct", 0.25), ("rotary_emb_base", 10000),
                              ("layer_norm_eps", 1e-5), ("hidden_act", "gelu"),
                              ("use_parallel_residual", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class PythiaForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return PythiaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        d = h // config.num_attention_heads
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_attention_heads,
            head_dim=d,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            activation=config.hidden_act,
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            parallel_residual=bool(config.use_parallel_residual),
            rotary_dim=int(d * config.rotary_pct),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return rope_ops.default_inv_freq(int(d * config.rotary_pct),
                                         float(config.rotary_emb_base))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.hidden_size
        nh = config.num_attention_heads
        d = h // nh

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        for i in range(config.num_hidden_layers):
            p = f"gpt_neox.layers.{i}."
            # fused QKV is interleaved per head: rows [h0_q, h0_k, h0_v, h1_q, ...]
            qkv = get(p + "attention.query_key_value.weight").reshape(nh, 3, d, h)
            qkv_b = get(p + "attention.query_key_value.bias").reshape(nh, 3, d)
            layers["wq"].append(
                np.ascontiguousarray(qkv[:, 0].reshape(nh * d, h).T))
            layers["wk"].append(
                np.ascontiguousarray(qkv[:, 1].reshape(nh * d, h).T))
            layers["wv"].append(
                np.ascontiguousarray(qkv[:, 2].reshape(nh * d, h).T))
            layers["bq"].append(qkv_b[:, 0].reshape(-1))
            layers["bk"].append(qkv_b[:, 1].reshape(-1))
            layers["bv"].append(qkv_b[:, 2].reshape(-1))
            layers["wo"].append(
                np.ascontiguousarray(get(p + "attention.dense.weight").T))
            layers["bo"].append(get(p + "attention.dense.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["wg"].append(
                np.ascontiguousarray(get(p + "mlp.dense_h_to_4h.weight").T))
            layers["bg"].append(get(p + "mlp.dense_h_to_4h.bias"))
            layers["wd"].append(
                np.ascontiguousarray(get(p + "mlp.dense_4h_to_h.weight").T))
            layers["bd"].append(get(p + "mlp.dense_4h_to_h.bias"))
        return {
            "embed": get("gpt_neox.embed_in.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("gpt_neox.final_layer_norm.weight"),
            "final_norm_b": get("gpt_neox.final_layer_norm.bias"),
            "lm_head": np.ascontiguousarray(get("embed_out.weight").T),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
