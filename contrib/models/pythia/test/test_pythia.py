"""pythia parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/pythia/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_pythia_parity():
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    from contrib.models.pythia.src.modeling_pythia import PythiaForCausalLM

    cfg = GPTNeoXConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=128,
                        rotary_pct=0.25, max_position_embeddings=128,
                        use_parallel_residual=True, hidden_act="gelu",
                        hidden_dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf = GPTNeoXForCausalLM(cfg).eval()
    _run_parity(PythiaForCausalLM, hf, cfg)
