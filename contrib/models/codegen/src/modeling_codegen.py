"""CodeGen (Salesforce) on the TPU framework (contrib port).

GPT-J-style block (shared-LN parallel residual, interleaved partial rotary,
plain biased gelu MLP, biased lm_head) with CodeGen's TPU-v4-era packed
qkv_proj: columns grouped into mp_num=4 blocks of [q | v | k], unpacked at
conversion into the standard per-projection layout (block-major head order is
self-consistent across q/k/v/out).
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class CodeGenInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("n_embd", "n_layer", "n_head", "vocab_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rotary_dim", 64), ("layer_norm_epsilon", 1e-5),
                              ("n_inner", None),
                              ("activation_function", "gelu_new"),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                if default is not None or not hasattr(self, attr):
                    setattr(self, attr, default)
        if self.n_inner is None:
            self.n_inner = 4 * self.n_embd


class CodeGenForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return CodeGenInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        d = config.n_embd // config.n_head
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.n_embd,
            num_layers=config.n_layer,
            num_heads=config.n_head,
            num_kv_heads=config.n_head,
            head_dim=d,
            intermediate_size=config.n_inner,
            rms_norm_eps=config.layer_norm_epsilon,
            norm_type="layer",
            norm_bias=True,
            activation=config.activation_function,
            mlp_kind="plain",
            mlp_bias=True,
            o_bias=False,
            parallel_residual=True,
            shared_ln=True,
            rotary_dim=int(config.rotary_dim),
            rope_interleaved=True,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(int(config.rotary_dim), 10000.0)

    def logical_axes(self) -> Dict:
        from neuronx_distributed_inference_tpu.models import base as model_base

        axes = model_base.param_logical_axes(self.arch_args)
        axes["lm_head_b"] = ("vocab",)
        return axes

    def init_random_params(self, key) -> Dict:
        import jax.numpy as jnp

        params = super().init_random_params(key)
        params["lm_head_b"] = jnp.zeros((self.arch_args.vocab_size,),
                                        self.tpu_config.jax_dtype)
        return params

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        E = config.n_embd
        ld = E // 4                              # mp_num = 4, local q/v/k width
        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "wo",
                                  "ln2", "ln2_b", "wg", "bg", "wd", "bd")}
        for i in range(config.n_layer):
            p = f"transformer.h.{i}."
            qkv = lin_t(p + "attn.qkv_proj.weight").reshape(E, 4, 3 * ld)
            layers["wq"].append(np.ascontiguousarray(
                qkv[:, :, 0:ld].reshape(E, E)))
            layers["wv"].append(np.ascontiguousarray(
                qkv[:, :, ld: 2 * ld].reshape(E, E)))
            layers["wk"].append(np.ascontiguousarray(
                qkv[:, :, 2 * ld:].reshape(E, E)))
            layers["wo"].append(lin_t(p + "attn.out_proj.weight"))
            ln = get(p + "ln_1.weight")
            layers["ln1"].append(ln)
            layers["ln1_b"].append(get(p + "ln_1.bias"))
            layers["ln2"].append(np.ones_like(ln))       # unused under shared_ln
            layers["ln2_b"].append(np.zeros_like(ln))
            layers["wg"].append(lin_t(p + "mlp.fc_in.weight"))
            layers["bg"].append(get(p + "mlp.fc_in.bias"))
            layers["wd"].append(lin_t(p + "mlp.fc_out.weight"))
            layers["bd"].append(get(p + "mlp.fc_out.bias"))
        return {
            "embed": get("transformer.wte.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "lm_head": lin_t("lm_head.weight"),
            "lm_head_b": get("lm_head.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
