"""codegen parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/codegen/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_codegen_parity():
    """CodeGen: mp_num=4 packed qkv (blocks of [q|v|k]) unpacked at conversion;
    block-major head order is self-consistent across projections."""
    from transformers import CodeGenConfig, CodeGenForCausalLM as HFCodeGen

    from contrib.models.codegen.src.modeling_codegen import CodeGenForCausalLM

    cfg = CodeGenConfig(vocab_size=256, n_embd=64, n_layer=2, n_head=4,
                        rotary_dim=8, n_inner=128, resid_pdrop=0.0,
                        embd_pdrop=0.0, attn_pdrop=0.0,
                        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFCodeGen(cfg).eval()
    _run_parity(CodeGenForCausalLM, hf, cfg)
