"""zamba parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/zamba/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_zamba_parity():
    """Zamba v1: shared-block hybrid with a MULTI-HEAD mamba1 mixer (per-head
    x_proj/dt_proj, interleaved x|z in_proj packing) and an adapter-free tied
    transformer block."""
    from transformers import ZambaConfig, ZambaForCausalLM as HFZamba

    from contrib.models.zamba.src.modeling_zamba import ZambaForCausalLM

    cfg = ZambaConfig(vocab_size=256, hidden_size=32, num_hidden_layers=4,
                      attn_layer_period=3, attn_layer_offset=1,
                      num_attention_heads=4, num_key_value_heads=4,
                      intermediate_size=64, mamba_d_state=8, mamba_d_conv=4,
                      mamba_expand=2, mamba_dt_rank=4, n_mamba_heads=2,
                      use_mamba_kernels=False,
                      max_position_embeddings=128, pad_token_id=0,
                      tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFZamba(cfg).eval()
    _run_parity(ZambaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
