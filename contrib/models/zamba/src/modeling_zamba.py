"""Zamba v1 (Zyphra shared-block hybrid, mamba1 backbone) on the TPU
framework (contrib port).

≈ reference contrib hybrid family. Zamba2's macro-structure — every layer a
mamba mixer, with ONE tied transformer block invoked at the hybrid positions
over concat(h, h0) and fed back through a per-layer linear — but with the
first-generation pieces: a MULTI-HEAD mamba1 selective-SSM mixer (per-head
x_proj/dt_proj, HF `ZambaMambaMixer.slow_forward`; prefill redesigned as an
associative scan over the diagonal recurrence), a shared block without LoRA
adapters (separate gate/up gated MLP), and NoPE attention at scale
(head_dim/2)^-0.5 over the doubled width.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)

ACTS = {"gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
        "silu": jax.nn.silu, "relu": jax.nn.relu}


@dataclass(frozen=True)
class ZambaArchArgs(ModelArchArgs):
    layer_kinds: Tuple[str, ...] = ()
    d_inner: int = 0
    d_state: int = 16
    d_conv: int = 4
    dt_rank: int = 0
    n_mamba_heads: int = 1
    hidden_act: str = "gelu"

    @property
    def mamba_head_dim(self) -> int:
        return self.d_inner // self.n_mamba_heads


def _ssm_terms(lp, xc, args):
    """Post-conv activations -> (dA, dBu, C) via the per-head projections."""
    b, t, _ = xc.shape
    nh, ih, s, r = (args.n_mamba_heads, args.mamba_head_dim, args.d_state,
                    args.dt_rank)
    xh = xc.reshape(b, t, nh, ih)
    pr = jnp.einsum("bthi,hri->bthr", xh, lp["x_proj"])      # (B,T,nh,R+2S)
    dt_r, b_m, c_m = pr[..., :r], pr[..., r : r + s], pr[..., r + s :]
    delta = jax.nn.softplus(
        (jnp.einsum("bthr,hir->bthi", dt_r, lp["dt_proj"])
         + lp["dt_bias"][None, None]).astype(jnp.float32))   # (B,T,nh,Ih)
    a = -jnp.exp(lp["a_log"].astype(jnp.float32)).reshape(args.d_inner, s)
    d_a = jnp.exp(delta.reshape(b, t, args.d_inner)[..., None]
                  * a[None, None])                           # (B,T,I,S)
    d_bu = (delta[..., None] * b_m[:, :, :, None, :].astype(jnp.float32)
            * xh.astype(jnp.float32)[..., None]
            ).reshape(b, t, args.d_inner, s)
    return d_a, d_bu, c_m.astype(jnp.float32)


def _finish(lp, h_states, xc, z, args, shape):
    """C-contraction + D skip + silu(z) gate + out projection."""
    b, t = shape
    nh, ih = args.n_mamba_heads, args.mamba_head_dim
    c_m = h_states[1]
    y = jnp.einsum("bthis,bths->bthi",
                   h_states[0].reshape(b, t, nh, ih, args.d_state), c_m)
    y = y.reshape(b, t, args.d_inner)
    y = y + xc.astype(jnp.float32) * lp["d_skip"].astype(
        jnp.float32).reshape(args.d_inner)[None, None]
    y = y * jax.nn.silu(z.astype(jnp.float32))
    return y.astype(lp["out_proj"].dtype) @ lp["out_proj"]


def _mixer_prefill(lp, hn, last_token_idx, args):
    b, t, _ = hn.shape
    w = args.d_conv
    proj = hn @ lp["in_proj"]                 # de-interleaved: [x(I) | z(I)]
    x, z = proj[..., : args.d_inner], proj[..., args.d_inner :]

    idx = last_token_idx[:, None] + 1 - w + jnp.arange(w)[None, :]
    gathered = jnp.take_along_axis(x, jnp.clip(idx, 0, t - 1)[:, :, None],
                                   axis=1)
    conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)
    xp = jnp.pad(x, ((0, 0), (w - 1, 0), (0, 0)))
    xc = sum(xp[:, j : j + t, :] * lp["conv_w"][j][None, None, :]
             for j in range(w)) + lp["conv_b"][None, None, :]
    xc = jax.nn.silu(xc)

    d_a, d_bu, c_m = _ssm_terms(lp, xc, args)
    valid = (jnp.arange(t)[None, :] <= last_token_idx[:, None])[..., None, None]
    d_a = jnp.where(valid, d_a, 1.0)
    d_bu = jnp.where(valid, d_bu, 0.0)

    def comb(l, r):
        return (r[0] * l[0], r[0] * l[1] + r[1])

    _, h_seq = jax.lax.associative_scan(comb, (d_a, d_bu), axis=1)
    ssm_state = jnp.take_along_axis(
        h_seq, last_token_idx[:, None, None, None], axis=1)[:, 0]
    out = _finish(lp, (h_seq, c_m), xc, z, args, (b, t))
    return out, conv_state.astype(hn.dtype), ssm_state


def _mixer_decode(lp, hn, conv_state, ssm_state, args):
    b = hn.shape[0]
    proj = hn @ lp["in_proj"]
    x, z = proj[..., : args.d_inner], proj[..., args.d_inner :]
    state = jnp.concatenate([conv_state[:, 1:], x[:, 0][:, None, :]], axis=1)
    xc = jnp.sum(state * lp["conv_w"][None, :, :], axis=1) + lp["conv_b"]
    xc = jax.nn.silu(xc)[:, None, :]
    d_a, d_bu, c_m = _ssm_terms(lp, xc, args)
    h = d_a[:, 0] * ssm_state + d_bu[:, 0]
    out = _finish(lp, (h[:, None], c_m), xc, z, args, (b, 1))
    return out, state.astype(conv_state.dtype), h


def _shared_block(params, hi, h, h0, mask, k_cache, v_cache, positions,
                  bucket, args):
    """One invocation of the tied transformer block (no internal residuals,
    no adapters — HF `ZambaAttentionDecoderLayer`)."""
    sp = params["shared"]
    b, t, _ = h.shape
    x = jnp.concatenate([h, h0], axis=-1)
    xn = rms_norm(x, sp["ln1"], args.rms_norm_eps)
    q = (xn @ sp["wq"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (xn @ sp["wk"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    v = (xn @ sp["wv"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    a = attend(q, k_att, v_att, mask=mask, scale=(args.head_dim / 2) ** -0.5)
    a = a.transpose(0, 2, 1, 3).reshape(b, t, -1) @ sp["wo"]

    hn = rms_norm(a, sp["ln2"], args.rms_norm_eps)
    act = ACTS[args.hidden_act]
    mlp = (act(hn @ sp["wg"]) * (hn @ sp["wu"])) @ sp["wd"]
    return mlp @ params["linear"][hi], k_cache, v_cache


def _forward(params, args: ZambaArchArgs, h, mask, cache, positions, bucket,
             last_token_idx):
    h0 = h
    ks, vs, convs, ssms = [], [], [], []
    hi = 0
    for li, kind in enumerate(args.layer_kinds):
        lp = params["layers"][li]
        if kind == "hybrid":
            t_states, kc, vc = _shared_block(
                params, hi, h, h0, mask, cache["k"][hi], cache["v"][hi],
                positions, bucket, args)
            ks.append(kc)
            vs.append(vc)
            hi += 1
        else:
            t_states = 0.0
        resid = h
        hn = rms_norm(h + t_states, lp["ln1"], args.rms_norm_eps)
        if positions is None:
            out, conv_state, ssm_state = _mixer_prefill(lp, hn, last_token_idx,
                                                        args)
        else:
            out, conv_state, ssm_state = _mixer_decode(
                lp, hn, cache["conv"][li], cache["ssm"][li], args)
        convs.append(conv_state)
        ssms.append(ssm_state)
        h = resid + out
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks) if ks else cache["k"],
                 "v": jnp.stack(vs) if vs else cache["v"],
                 "conv": jnp.stack(convs), "ssm": jnp.stack(ssms)}
    return h, out_cache


def prefill_forward(params, args: ZambaArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    t = input_ids.shape[1]
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h_last @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: ZambaArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Zamba decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= position_ids[:, None, None, None]
    h, out_cache = _forward(params, args, h, mask, cache, position_ids,
                            decode_bucket, None)
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class ZambaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size", "mamba_d_state",
                           "layers_block_type")

    def add_derived_config(self) -> None:
        for attr, default in (("rms_norm_eps", 1e-5), ("mamba_d_conv", 4),
                              ("mamba_expand", 2), ("n_mamba_heads", 1),
                              ("hidden_act", "gelu"),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "attention_head_dim") or \
                self.attention_head_dim is None:
            self.attention_head_dim = (2 * self.hidden_size
                                       // self.num_attention_heads)
        if getattr(self, "mamba_dt_rank", None) in (None, "auto"):
            import math
            self.mamba_dt_rank = math.ceil(self.hidden_size / 16)
        kvh = getattr(self, "num_key_value_heads", None)
        if kvh is not None and kvh != self.num_attention_heads:
            raise ValueError("Zamba GQA is not ported")
        if getattr(self, "add_bias_linear", False):
            raise ValueError("Zamba add_bias_linear=True is not ported")
        if getattr(self, "hidden_mamba_act", "silu") != "silu":
            raise ValueError("Zamba hidden_mamba_act must be silu")


class ZambaForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config,
                                  "Zamba (shared-block mamba1 hybrid)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return ZambaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ZambaArchArgs:
        d_inner = int(config.mamba_expand * config.hidden_size)
        return ZambaArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_attention_heads,
            head_dim=int(config.attention_head_dim),
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            layer_kinds=tuple(config.layers_block_type),
            d_inner=d_inner,
            d_state=int(config.mamba_d_state),
            d_conv=int(config.mamba_d_conv),
            dt_rank=int(config.mamba_dt_rank),
            n_mamba_heads=int(config.n_mamba_heads),
            hidden_act=str(config.hidden_act),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # NoPE: identity rotation table (unused by this family's forward)
        return np.zeros((int(config.attention_head_dim) // 2,), np.float32)

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: ZambaArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        n_hyb = sum(1 for k in a.layer_kinds if k == "hybrid")
        self.kv_cache = {
            "k": jnp.zeros((n_hyb, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((n_hyb, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((a.num_layers, b, a.d_conv, a.d_inner), dt),
            "ssm": jnp.zeros((a.num_layers, b, a.d_inner, a.d_state),
                             jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias"}

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        hyb_ids = [i for i, k in enumerate(config.layers_block_type)
                   if k == "hybrid"]
        st = f"model.layers.{hyb_ids[0]}.shared_transf."
        shared = {
            "ln1": get(st + "input_layernorm.weight"),
            "wq": lin_t(st + "self_attn.q_proj.weight"),
            "wk": lin_t(st + "self_attn.k_proj.weight"),
            "wv": lin_t(st + "self_attn.v_proj.weight"),
            "wo": lin_t(st + "self_attn.o_proj.weight"),
            "ln2": get(st + "pre_ff_layernorm.weight"),
            "wg": lin_t(st + "feed_forward.gate_proj.weight"),
            "wu": lin_t(st + "feed_forward.up_proj.weight"),
            "wd": lin_t(st + "feed_forward.down_proj.weight"),
        }
        linear = np.stack([lin_t(f"model.layers.{i}.linear.weight")
                           for i in hyb_ids])

        layers = []
        for i, kind in enumerate(config.layers_block_type):
            p = f"model.layers.{i}."
            mx = (p + "mamba_decoder." if kind == "hybrid" else p)
            in_proj = lin_t(mx + "mamba.in_proj.weight")       # (H, 2I)
            # HF packs x/z channel-pairs interleaved (view(B, I, 2, T).chunk):
            # even columns are the conv/SSM path, odd columns the silu gate
            in_proj = np.concatenate([in_proj[:, 0::2], in_proj[:, 1::2]],
                                     axis=1)
            lp = {
                "ln1": get(mx + "input_layernorm.weight"),
                "in_proj": np.ascontiguousarray(in_proj),
                "conv_w": np.ascontiguousarray(
                    get(mx + "mamba.conv1d.weight")[:, 0, :].T),
                "conv_b": get(mx + "mamba.conv1d.bias"),
                "x_proj": get(mx + "mamba.x_proj_weight"),     # (nh, R+2S, Ih)
                "dt_proj": get(mx + "mamba.dt_proj_weight"),   # (nh, Ih, R)
                "dt_bias": get(mx + "mamba.dt_proj_bias"),     # (nh, Ih)
                "a_log": get(mx + "mamba.A_log"),              # (nh, Ih, S)
                "d_skip": get(mx + "mamba.D"),                 # (nh, Ih)
                "out_proj": lin_t(mx + "mamba.out_proj.weight"),
            }
            layers.append(lp)
        out = {
            "embed": get("model.embed_tokens.weight"),
            "shared": shared,
            "linear": linear,
            "layers": layers,
            "final_norm": get("model.final_layernorm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
