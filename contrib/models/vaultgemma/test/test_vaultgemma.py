"""vaultgemma parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/vaultgemma/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_vaultgemma_parity():
    """VaultGemma: gemma2 without the sandwich branch norms."""
    from transformers import VaultGemmaConfig, VaultGemmaForCausalLM as HFVg

    from contrib.models.vaultgemma.src.modeling_vaultgemma import (
        VaultGemmaForCausalLM)

    cfg = VaultGemmaConfig(vocab_size=256, hidden_size=64,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=2, intermediate_size=128,
                           head_dim=16, query_pre_attn_scalar=16,
                           sliding_window=8, attn_logit_softcapping=50.0,
                           final_logit_softcapping=30.0,
                           layer_types=["sliding_attention", "full_attention"],
                           hidden_activation="gelu_pytorch_tanh",
                           pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFVg(cfg).eval()
    # eos_token_id=1: HF generate stops at VaultGemma's default eos and pads
    _run_parity(VaultGemmaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3,
                eos_token_id=1)
