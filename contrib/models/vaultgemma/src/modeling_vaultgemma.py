"""VaultGemma (Google DP-trained gemma) on the TPU framework (contrib port).

≈ reference contrib gemma family. Gemma-2 architecture (zero-centered norms,
soft-caps, sliding/full pattern, query_pre_attn_scalar scaling) WITHOUT the
sandwich branch norms — `VaultGemmaDecoderLayer` keeps only input_layernorm
and pre_feedforward_layernorm. Conversion is inherited: gemma2's converter
detects the absent sandwich-norm weights.
"""

import dataclasses

from contrib.models.gemma2.src.modeling_gemma2 import (Gemma2ForCausalLM,
                                                       Gemma2InferenceConfig)
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs


class VaultGemmaInferenceConfig(Gemma2InferenceConfig):
    pass


class VaultGemmaForCausalLM(Gemma2ForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return VaultGemmaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return dataclasses.replace(super().arch_args_from_config(config),
                                   sandwich_norms=False)
