"""GPT-BigCode (SantaCoder/StarCoder1) on the TPU framework (contrib port).

≈ reference contrib starcoder family. GPT-2 block (learned positions, biased
LayerNorm, plain gelu-tanh MLP, tied head) with multi-query attention: the
fused `c_attn` packs [q(H) | k(head_dim) | v(head_dim)] and all query heads
share the single KV head (HF `GPTBigCodeAttention`, multi_query=True). Unlike
gpt2's Conv1D, BigCode stores nn.Linear weights, so projections transpose at
conversion.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class GPTBigCodeInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("n_embd", "n_layer", "n_head", "vocab_size",
                           "n_positions")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5),
                              ("activation_function", "gelu_pytorch_tanh"),
                              ("multi_query", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if getattr(self, "n_inner", None) is None:
            self.n_inner = 4 * self.n_embd
        if not getattr(self, "scale_attn_weights", True):
            raise ValueError("scale_attn_weights=False is not ported")


class GPTBigCodeForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GPTBigCodeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.n_embd
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.n_layer,
            num_heads=config.n_head,
            num_kv_heads=1 if config.multi_query else config.n_head,
            head_dim=h // config.n_head,
            intermediate_size=config.n_inner,
            rms_norm_eps=config.layer_norm_epsilon,
            activation=config.activation_function,
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            learned_pos=True,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # learned positions: rope collapses to identity via a zero frequency table
        return np.zeros(((config.n_embd // config.n_head) // 2,), np.float32)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.n_embd
        kv_dim = (h // config.n_head) if config.multi_query else h

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        nh, hd = config.n_head, h // config.n_head
        for i in range(config.n_layer):
            p = f"transformer.h.{i}."
            c_attn = lin_t(p + "attn.c_attn.weight")
            c_attn_b = get(p + "attn.c_attn.bias")
            if config.multi_query:
                # (H, H + 2·head_dim): [q(H) | k(hd) | v(hd)], one shared KV head
                qkv_w = (c_attn[:, :h], c_attn[:, h : h + kv_dim],
                         c_attn[:, h + kv_dim :])
                qkv_b = (c_attn_b[:h], c_attn_b[h : h + kv_dim],
                         c_attn_b[h + kv_dim :])
            else:
                # MHA packs per-head [q|k|v] chunks of head_dim
                # (`GPTBigCodeAttention.forward`: view(.., nh, 3·hd).split)
                w3 = c_attn.reshape(h, nh, 3, hd)
                b3 = c_attn_b.reshape(nh, 3, hd)
                qkv_w = tuple(np.ascontiguousarray(w3[:, :, j].reshape(h, h))
                              for j in range(3))
                qkv_b = tuple(b3[:, j].reshape(h) for j in range(3))
            for key, val in zip(("wq", "wk", "wv"), qkv_w):
                layers[key].append(val)
            for key, val in zip(("bq", "bk", "bv"), qkv_b):
                layers[key].append(val)
            layers["wo"].append(lin_t(p + "attn.c_proj.weight"))
            layers["bo"].append(get(p + "attn.c_proj.bias"))
            layers["ln1"].append(get(p + "ln_1.weight"))
            layers["ln1_b"].append(get(p + "ln_1.bias"))
            layers["ln2"].append(get(p + "ln_2.weight"))
            layers["ln2_b"].append(get(p + "ln_2.bias"))
            layers["wg"].append(lin_t(p + "mlp.c_fc.weight"))
            layers["bg"].append(get(p + "mlp.c_fc.bias"))
            layers["wd"].append(lin_t(p + "mlp.c_proj.weight"))
            layers["bd"].append(get(p + "mlp.c_proj.bias"))
        out = {
            "embed": get("transformer.wte.weight"),
            "pos_embed": get("transformer.wpe.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not getattr(config, "tie_word_embeddings", True):
            out["lm_head"] = lin_t("lm_head.weight")
        return out
