"""gpt_bigcode parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gpt_bigcode/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_gpt_bigcode_parity():
    """GPT-BigCode (StarCoder1): GPT-2 block with multi-query attention —
    fused c_attn packs [q | k(1 head) | v(1 head)]."""
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM as HFBig

    from contrib.models.gpt_bigcode.src.modeling_gpt_bigcode import (
        GPTBigCodeForCausalLM)

    cfg = GPTBigCodeConfig(vocab_size=256, n_positions=128, n_embd=64,
                           n_layer=2, n_head=4, multi_query=True,
                           activation_function="gelu_pytorch_tanh",
                           resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = HFBig(cfg).eval()
    _run_parity(GPTBigCodeForCausalLM, hf, cfg)


def test_gpt_bigcode_mha_parity():
    """multi_query=False: the fused c_attn interleaves per-head [q|k|v]
    chunks, a different layout than the MQA [q|k|v] blocks."""
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM as HFBig

    from contrib.models.gpt_bigcode.src.modeling_gpt_bigcode import (
        GPTBigCodeForCausalLM)

    cfg = GPTBigCodeConfig(vocab_size=256, n_positions=128, n_embd=64,
                           n_layer=2, n_head=4, multi_query=False,
                           activation_function="gelu_pytorch_tanh",
                           resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(1)
    hf = HFBig(cfg).eval()
    _run_parity(GPTBigCodeForCausalLM, hf, cfg)
