"""recurrentgemma parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/recurrentgemma/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_recurrentgemma_parity():
    """Griffin / RG-LRU: the first non-KV recurrent-state cache in the hub.
    Prefill runs the recurrence as an associative scan; parity vs HF exercises
    the recurrence math, the conv tail handoff, and the mixed cache pytree."""
    from transformers import (RecurrentGemmaConfig,
                              RecurrentGemmaForCausalLM as HFRg)

    from contrib.models.recurrentgemma.src.modeling_recurrentgemma import (
        RecurrentGemmaForCausalLM)

    cfg = RecurrentGemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=192,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        lru_width=64, conv1d_width=4, attention_window_size=16,
        embeddings_scale_by_sqrt_dim=True, logits_soft_cap=30.0,
        partial_rotary_factor=0.5, pad_token_id=0,
        block_types=["recurrent", "recurrent", "attention"])
    torch.manual_seed(0)
    hf = HFRg(cfg).eval()
    _run_parity(RecurrentGemmaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3,
                eos_token_id=1)
