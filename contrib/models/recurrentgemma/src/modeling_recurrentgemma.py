"""RecurrentGemma (Griffin) on the TPU framework (contrib port).

≈ reference `contrib/models/recurrentgemma-2b-it/`. The first NON-KV state cache
in the hub: Griffin interleaves RG-LRU recurrent blocks (2 per attention block)
whose per-layer state is a (B, lru_width) fp32 recurrence vector plus a
(B, conv_width-1, lru_width) causal-conv tail — not a KV cache. TPU redesign:

- **Prefill runs the linear recurrence as a `jax.lax.associative_scan`**
  (h_t = a_t h_{t-1} + b_t is associative in (a, b)), so the sequential RG-LRU
  becomes a log-depth parallel scan on the VPU instead of an O(S) loop — the
  idiomatic TPU form of the recurrence (the HF reference loops over t).
- Right-padded prefill freezes each row's recurrence at its true length
  (a=1, b=0 on padding), so the carried decode state is exactly the state at
  the last real token; the conv tail gathers the last W-1 real inputs.
- Decode is one fused step per token: conv tail dot + single recurrence update,
  with the attention layers' sliding-window KV riding the same cache pytree.
- Attention blocks: GQA + partial rotary + sliding window + biased o_proj.
- RG-LRU math follows HF `RecurrentGemmaRglru`: block-diagonal sigmoid gates,
  a = exp(-8 c r_t softplus(Λ)), input scaled by sqrt(1 - a²) (1 at position 0),
  fp32 accumulation.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class RecurrentGemmaArchArgs(ModelArchArgs):
    """Griffin extension: block kinds + recurrent geometry."""

    lru_width: int = 0
    conv1d_width: int = 4
    attention_window_size: int = 2048
    block_types: Tuple[str, ...] = ()        # per-layer "recurrent" | "attention"


# --- RG-LRU core ----------------------------------------------------------------------


def _rg_lru_gates(lp, x, args):
    """x (B, S, lru) -> (a, gated, mult), all (B, S, lru) fp32.

    Block-diagonal gate projections per head (HF `input_gate_weight`
    (nh, bw, bw)); a = exp(-8 * r * softplus(Λ)); gated = x·i_gate;
    mult = sqrt(1 - a²). The recurrence input is gated * mult (with mult
    replaced by 1 at position-0 resets — callers apply that)."""
    bsz, s, lru = x.shape
    nh = args.num_heads
    bw = lru // nh
    xh = x.reshape(bsz, s, nh, bw).astype(jnp.float32)
    i_gate = jax.nn.sigmoid(
        jnp.einsum("bsnw,nwv->bsnv", xh, lp["lru_wi"].astype(jnp.float32))
        + lp["lru_bi"].astype(jnp.float32)).reshape(bsz, s, lru)
    r_gate = jax.nn.sigmoid(
        jnp.einsum("bsnw,nwv->bsnv", xh, lp["lru_wr"].astype(jnp.float32))
        + lp["lru_br"].astype(jnp.float32)).reshape(bsz, s, lru)
    log_a = -8.0 * r_gate * jax.nn.softplus(lp["lru_lambda"].astype(jnp.float32))
    a = jnp.exp(log_a)
    mult = jnp.sqrt(jnp.maximum(1.0 - jnp.exp(2.0 * log_a), 0.0))
    gated = x.astype(jnp.float32) * i_gate
    return a, gated, mult


def _conv_causal(lp, x, args):
    """Depthwise causal conv over the sequence: x (B, S, lru) -> (B, S, lru).
    Kernel lp["conv_w"] (W, lru) (tap j multiplies x[t - (W-1) + j]), bias (lru,)."""
    w = args.conv1d_width
    xp = jnp.pad(x, ((0, 0), (w - 1, 0), (0, 0)))
    s = x.shape[1]
    out = sum(xp[:, j : j + s, :] * lp["conv_w"][j][None, None, :]
              for j in range(w))
    return out + lp["conv_b"][None, None, :]


def _recurrent_block_prefill(lp, hn, position_ids, last_token_idx, args):
    """Full-sequence recurrent block; returns (out (B, S, H), conv_state, lru_state)."""
    w = args.conv1d_width
    y = jax.nn.gelu(hn @ lp["wy"] + lp["by"], approximate=True)
    x = hn @ lp["wx"] + lp["bx"]                             # (B, S, lru)

    # conv tail for decode: the last W-1 REAL inputs per row (zeros if shorter)
    s = x.shape[1]
    idx = last_token_idx[:, None] + 1 - (w - 1) + jnp.arange(w - 1)[None, :]
    gathered = jnp.take_along_axis(
        x, jnp.clip(idx, 0, s - 1)[:, :, None], axis=1)
    conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)

    xc = _conv_causal(lp, x, args)
    a, gated, mult = _rg_lru_gates(lp, xc, args)
    reset = (position_ids == 0)[:, :, None]
    valid = (jnp.arange(s)[None, :] <= last_token_idx[:, None])[:, :, None]
    # position-0 reset: a = 0, input multiplier = 1 (HF `reset + ~reset * mult`)
    b = gated * jnp.where(reset, 1.0, mult)
    a = jnp.where(reset, 0.0, a)
    # freeze padded positions so the carried state is the last real token's
    a = jnp.where(valid, a, 1.0)
    b = jnp.where(valid, b, 0.0)

    def comb(l, r):
        return (r[0] * l[0], r[0] * l[1] + r[1])

    _, h_seq = jax.lax.associative_scan(comb, (a, b), axis=1)    # (B, S, lru) fp32
    lru_state = jnp.take_along_axis(
        h_seq, last_token_idx[:, None, None], axis=1)[:, 0]      # (B, lru)

    out = (h_seq.astype(hn.dtype) * y) @ lp["wo_r"] + lp["bo_r"]
    return out, conv_state.astype(hn.dtype), lru_state


def _recurrent_block_decode(lp, hn, conv_state, lru_state, args):
    """One-token recurrent step. hn (B, 1, H); returns (out, conv_state, lru_state)."""
    w = args.conv1d_width
    y = jax.nn.gelu(hn @ lp["wy"] + lp["by"], approximate=True)
    x = (hn @ lp["wx"] + lp["bx"])[:, 0]                     # (B, lru)
    full = jnp.concatenate([conv_state, x[:, None, :]], axis=1)   # (B, W, lru)
    xc = jnp.sum(full * lp["conv_w"][None, :, :], axis=1) + lp["conv_b"]
    a, gated, mult = _rg_lru_gates(lp, xc[:, None, :], args)
    h = a[:, 0] * lru_state + (gated * mult)[:, 0]           # (B, lru) fp32
    out = (h.astype(hn.dtype)[:, None, :] * y) @ lp["wo_r"] + lp["bo_r"]
    return out, full[:, 1:, :].astype(conv_state.dtype), h


# --- attention block ------------------------------------------------------------------


def _attn_block(lp, hn, cos, sin, mask, k_cache, v_cache, positions, bucket, args):
    """Sliding-window GQA with partial rotary; mirrors models/base semantics over
    one dense cache layer. Returns (out, k_cache, v_cache)."""
    b, s, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, s, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, s, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    v = (hn @ lp["wv"]).reshape(b, s, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    rd = args.rotary_dim
    q1, k1 = rope_ops.apply_rotary(q[..., :rd], k[..., :rd], cos, sin)
    q = jnp.concatenate([q1, q[..., rd:]], axis=-1)
    k = jnp.concatenate([k1, k[..., rd:]], axis=-1)

    if positions is None:                                    # prefill
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:                                                    # decode
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)

    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, args.q_size)
    return attn @ lp["wo"] + lp["bo"], k_cache, v_cache


# --- full forwards --------------------------------------------------------------------


def _mlp(lp, hn):
    gate = jax.nn.gelu(hn @ lp["wg"] + lp["bg"], approximate=True)
    return (gate * (hn @ lp["wu"] + lp["bu"])) @ lp["wd"] + lp["bd"]


def prefill_forward(params, args: RecurrentGemmaArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
    s = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(s, s)[None, None]
    kv_pos = position_ids[:, None, None, :]
    q_pos = position_ids[:, None, :, None]
    mask &= kv_pos > q_pos - args.attention_window_size

    ks, vs, convs, lrus = [], [], [], []
    ai = ri = 0
    for li, kind in enumerate(args.block_types):
        lp = jax.tree.map(lambda p: p[li] if isinstance(p, jnp.ndarray) else p,
                          params["layers"])
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps, zero_centered=True)
        if kind == "attention":
            out, kc, vc = _attn_block(lp, hn, cos, sin, mask, cache["k"][ai],
                                      cache["v"][ai], None, None, args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        else:
            out, conv_state, lru_state = _recurrent_block_prefill(
                lp, hn, position_ids, last_token_idx, args)
            convs.append(conv_state)
            lrus.append(lru_state)
            ri += 1
        h = h + out
        resid = h
        hn = rms_norm(h, lp["ln2"], args.rms_norm_eps, zero_centered=True)
        h = resid + _mlp(lp, hn)

    h = rms_norm(h, params["final_norm"], args.rms_norm_eps, zero_centered=True)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["embed"].T).astype(jnp.float32)
    if args.final_logits_soft_cap is not None:
        cap = args.final_logits_soft_cap
        logits = cap * jnp.tanh(logits / cap)
    out_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs),
                 "conv": jnp.stack(convs), "lru": jnp.stack(lrus)}
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: RecurrentGemmaArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("RecurrentGemma decode is single-token only (the "
                         "recurrence carries one state per row)")
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
    pos_grid = position_ids[:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    q_pos = pos_grid[:, None, :, None]
    mask = (kv_pos <= q_pos) & (kv_pos > q_pos - args.attention_window_size)

    ks, vs, convs, lrus = [], [], [], []
    ai = ri = 0
    for li, kind in enumerate(args.block_types):
        lp = jax.tree.map(lambda p: p[li] if isinstance(p, jnp.ndarray) else p,
                          params["layers"])
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps, zero_centered=True)
        if kind == "attention":
            out, kc, vc = _attn_block(lp, hn, cos, sin, mask, cache["k"][ai],
                                      cache["v"][ai], position_ids, decode_bucket,
                                      args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        else:
            out, conv_state, lru_state = _recurrent_block_decode(
                lp, hn, cache["conv"][ri], cache["lru"][ri], args)
            convs.append(conv_state)
            lrus.append(lru_state)
            ri += 1
        h = h + out
        resid = h
        hn = rms_norm(h, lp["ln2"], args.rms_norm_eps, zero_centered=True)
        h = resid + _mlp(lp, hn)

    h = rms_norm(h, params["final_norm"], args.rms_norm_eps, zero_centered=True)
    logits = (h @ params["embed"].T).astype(jnp.float32)
    if args.final_logits_soft_cap is not None:
        cap = args.final_logits_soft_cap
        logits = cap * jnp.tanh(logits / cap)
    out_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs),
                 "conv": jnp.stack(convs), "lru": jnp.stack(lrus)}
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


# --- application ----------------------------------------------------------------------


class RecurrentGemmaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("partial_rotary_factor", 0.5),
                              ("conv1d_width", 4), ("attention_window_size", 2048),
                              ("logits_soft_cap", 30.0),
                              ("attention_bias", False),
                              ("embeddings_scale_by_sqrt_dim", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "lru_width") or self.lru_width is None:
            self.lru_width = self.hidden_size
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if not hasattr(self, "block_types") or not self.block_types:
            self.block_types = ["recurrent", "recurrent", "attention"]

    def layer_block_types(self):
        pattern = list(self.block_types)
        return tuple(pattern[i % len(pattern)]
                     for i in range(self.num_hidden_layers))


class RecurrentGemmaForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "RecurrentGemma (Griffin)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return RecurrentGemmaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> RecurrentGemmaArchArgs:
        if getattr(config, "attention_bias", False):
            raise ValueError("biased q/k/v projections not ported yet")
        return RecurrentGemmaArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size // 2,
            rms_norm_eps=config.rms_norm_eps,
            rotary_dim=int(config.head_dim * float(config.partial_rotary_factor)),
            embedding_multiplier=(float(config.hidden_size) ** 0.5
                                  if config.embeddings_scale_by_sqrt_dim else 1.0),
            final_logits_soft_cap=float(config.logits_soft_cap),
            tie_word_embeddings=True,
            lru_width=int(config.lru_width),
            conv1d_width=int(config.conv1d_width),
            attention_window_size=int(config.attention_window_size),
            block_types=config.layer_block_types(),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        rd = int(config.head_dim * float(config.partial_rotary_factor))
        return rope_ops.default_inv_freq(rd, float(config.rope_theta))

    # --- cache ------------------------------------------------------------------
    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: RecurrentGemmaArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        n_att = sum(1 for k in a.block_types if k == "attention")
        n_rec = len(a.block_types) - n_att
        dt = self.tpu_config.jax_dtype
        self.kv_cache = {
            "k": jnp.zeros((max(n_att, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((max(n_att, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((max(n_rec, 1), b, a.conv1d_width - 1,
                               a.lru_width), dt),
            "lru": jnp.zeros((max(n_rec, 1), b, a.lru_width), jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if arr is host_params.get(
                    "rope_inv_freq") else dtype)
            return jax.device_put(arr)

        params = jax.tree.map(_put, host_params)
        params["rope_inv_freq"] = jax.device_put(
            np.asarray(host_params["rope_inv_freq"], np.float32))
        # keep the RG-LRU decay parameter fp32 (the recurrence accumulates fp32)
        params["layers"]["lru_lambda"] = jax.device_put(
            np.asarray(host_params["layers"]["lru_lambda"], np.float32))
        self.params = params
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        kinds = config.layer_block_types()
        L = config.num_hidden_layers
        lru = config.lru_width
        zeros = {
            "wq": np.zeros((config.hidden_size, config.num_attention_heads
                            * config.head_dim), np.float32),
            "wk": np.zeros((config.hidden_size, config.num_key_value_heads
                            * config.head_dim), np.float32),
            "wv": np.zeros((config.hidden_size, config.num_key_value_heads
                            * config.head_dim), np.float32),
            "wo": np.zeros((config.num_attention_heads * config.head_dim,
                            config.hidden_size), np.float32),
            "bo": np.zeros((config.hidden_size,), np.float32),
            "wy": np.zeros((config.hidden_size, lru), np.float32),
            "by": np.zeros((lru,), np.float32),
            "wx": np.zeros((config.hidden_size, lru), np.float32),
            "bx": np.zeros((lru,), np.float32),
            "wo_r": np.zeros((lru, config.hidden_size), np.float32),
            "bo_r": np.zeros((config.hidden_size,), np.float32),
            "conv_w": np.zeros((config.conv1d_width, lru), np.float32),
            "conv_b": np.zeros((lru,), np.float32),
            "lru_lambda": np.zeros((lru,), np.float32),
            "lru_wi": np.zeros((config.num_attention_heads,
                                lru // config.num_attention_heads,
                                lru // config.num_attention_heads), np.float32),
            "lru_bi": np.zeros((config.num_attention_heads,
                                lru // config.num_attention_heads), np.float32),
            "lru_wr": np.zeros((config.num_attention_heads,
                                lru // config.num_attention_heads,
                                lru // config.num_attention_heads), np.float32),
            "lru_br": np.zeros((config.num_attention_heads,
                                lru // config.num_attention_heads), np.float32),
        }
        layers: Dict[str, list] = {k: [] for k in
                                   list(zeros) + ["ln1", "ln2", "wg", "bg",
                                                  "wu", "bu", "wd", "bd"]}
        for i in range(L):
            p = f"model.layers.{i}."
            t = p + "temporal_block."
            layers["ln1"].append(get(p + "temporal_pre_norm.weight"))
            layers["ln2"].append(get(p + "channel_pre_norm.weight"))
            layers["wg"].append(lin_t(p + "mlp_block.gate_proj.weight"))
            layers["bg"].append(get(p + "mlp_block.gate_proj.bias"))
            layers["wu"].append(lin_t(p + "mlp_block.up_proj.weight"))
            layers["bu"].append(get(p + "mlp_block.up_proj.bias"))
            layers["wd"].append(lin_t(p + "mlp_block.down_proj.weight"))
            layers["bd"].append(get(p + "mlp_block.down_proj.bias"))
            filled = dict(zeros)
            if kinds[i] == "attention":
                filled["wq"] = lin_t(t + "q_proj.weight")
                filled["wk"] = lin_t(t + "k_proj.weight")
                filled["wv"] = lin_t(t + "v_proj.weight")
                filled["wo"] = lin_t(t + "o_proj.weight")
                filled["bo"] = get(t + "o_proj.bias")
            else:
                filled["wy"] = lin_t(t + "linear_y.weight")
                filled["by"] = get(t + "linear_y.bias")
                filled["wx"] = lin_t(t + "linear_x.weight")
                filled["bx"] = get(t + "linear_x.bias")
                filled["wo_r"] = lin_t(t + "linear_out.weight")
                filled["bo_r"] = get(t + "linear_out.bias")
                # HF conv (lru, 1, W): tap j multiplies x[t - (W-1) + j]
                filled["conv_w"] = np.ascontiguousarray(
                    get(t + "conv_1d.weight")[:, 0, :].T)
                filled["conv_b"] = get(t + "conv_1d.bias")
                filled["lru_lambda"] = get(t + "rg_lru.recurrent_param")
                filled["lru_wi"] = get(t + "rg_lru.input_gate_weight")
                filled["lru_bi"] = get(t + "rg_lru.input_gate_bias")
                filled["lru_wr"] = get(t + "rg_lru.recurrent_gate_weight")
                filled["lru_br"] = get(t + "rg_lru.recurrent_gate_bias")
            for k, v in filled.items():
                layers[k].append(v)
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.final_norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
