"""phimoe parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/phimoe/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_phimoe_parity():
    from transformers import PhimoeConfig, PhimoeForCausalLM as HFPhimoe

    from contrib.models.phimoe.src.modeling_phimoe import PhimoeForCausalLM

    cfg = PhimoeConfig(vocab_size=256, hidden_size=64, intermediate_size=96,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, num_local_experts=4,
                       num_experts_per_tok=2, router_jitter_noise=0.01,
                       attention_bias=True, lm_head_bias=True,
                       pad_token_id=0, rope_scaling=None,
                       sliding_window=None, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFPhimoe(cfg).eval()
    _run_parity(PhimoeForCausalLM, hf, cfg, atol=1e-3, rtol=1e-3)
