"""Phi-3.5-MoE on the TPU framework (contrib port).

≈ reference `contrib/models/Phi-3.5-MoE-instruct/`. Mixtral-geometry MoE with
the PhiMoE specifics: biased LayerNorms (not RMSNorm), biased attention/output
projections, a biased lm_head, and **sparsemixer** routing — two sequential
argmax picks each weighted by a softmax over its jitter band
(ops/moe.py router_mode="sparsemixer", inference path of HF `sparsemixer`).
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class PhimoeInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "num_local_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("router_jitter_noise", 0.01),
                              ("attention_bias", True), ("lm_head_bias", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class PhimoeForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return PhimoeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            norm_type="layer",
            norm_bias=True,
            attention_bias=bool(config.attention_bias),
            o_bias=bool(config.attention_bias),
            moe=MoEArgs(num_experts=config.num_local_experts,
                        experts_per_tok=config.num_experts_per_tok,
                        router_mode="sparsemixer",
                        router_jitter=float(config.router_jitter_noise)),
        )

    def logical_axes(self) -> Dict:
        from neuronx_distributed_inference_tpu.models import base as model_base

        axes = model_base.param_logical_axes(self.arch_args)
        axes["lm_head_b"] = ("vocab",)
        return axes

    def init_random_params(self, key) -> Dict:
        import jax.numpy as jnp

        params = super().init_random_params(key)
        params["lm_head_b"] = jnp.zeros((self.arch_args.vocab_size,),
                                        self.tpu_config.jax_dtype)
        return params

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        E = config.num_local_experts
        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv",
                                  "bq", "bk", "bv", "wo", "bo",
                                  "ln2", "ln2_b", "router", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["bo"].append(get(p + "self_attn.o_proj.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            m = p + "block_sparse_moe."
            layers["router"].append(lin_t(m + "gate.weight"))
            # experts: w1 = gate, w3 = up, w2 = down (Mixtral naming)
            layers["wg"].append(np.stack(
                [lin_t(m + f"experts.{e}.w1.weight") for e in range(E)]))
            layers["wu"].append(np.stack(
                [lin_t(m + f"experts.{e}.w3.weight") for e in range(E)]))
            layers["wd"].append(np.stack(
                [lin_t(m + f"experts.{e}.w2.weight") for e in range(E)]))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "final_norm_b": get("model.norm.bias"),
            "lm_head": lin_t("lm_head.weight"),
            "lm_head_b": get("lm_head.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
