"""xglm parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/xglm/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_xglm_parity():
    """XGLM: computed fairseq sinusoidal positions (offset 2) materialized into
    the learned-position table; scaled embeddings; biased pre-LN decoder."""
    from transformers import XGLMConfig, XGLMForCausalLM as HFXglm

    from contrib.models.xglm.src.modeling_xglm import XGLMForCausalLM

    cfg = XGLMConfig(vocab_size=256, d_model=64, ffn_dim=128, num_layers=2,
                     attention_heads=4, dropout=0.0, attention_dropout=0.0,
                     activation_dropout=0.0, scale_embedding=True,
                     pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFXglm(cfg).eval()
    _run_parity(XGLMForCausalLM, hf, cfg)
