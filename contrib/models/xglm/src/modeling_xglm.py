"""XGLM (Meta multilingual GPT) on the TPU framework (contrib port).

Pre-LN decoder with FIXED sinusoidal positions (fairseq convention: computed,
not stored — materialized into the learned-position table at conversion, with
the fairseq +2 offset), sqrt(d_model)-scaled embeddings, biased plain-gelu
FFN, tied head.
"""

import math
from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


def sinusoidal_table(num_positions: int, dim: int, padding_idx: int = 1
                     ) -> np.ndarray:
    """fairseq/XGLM sinusoidal embedding table ([sin | cos] halves)."""
    half = dim // 2
    freq = np.exp(np.arange(half, dtype=np.float64)
                  * -(math.log(10000.0) / (half - 1)))
    pos = np.arange(num_positions, dtype=np.float64)[:, None] * freq[None, :]
    table = np.concatenate([np.sin(pos), np.cos(pos)], axis=1)
    if dim % 2 == 1:
        table = np.concatenate([table, np.zeros((num_positions, 1))], axis=1)
    table[padding_idx] = 0.0
    return table.astype(np.float32)


class XGLMInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("d_model", "num_layers", "attention_heads",
                           "vocab_size", "ffn_dim")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_eps", 1e-5), ("scale_embedding", True),
                              ("max_position_embeddings", 2048),
                              ("activation_function", "gelu"),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class XGLMForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return XGLMInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        d = config.d_model // config.attention_heads
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.d_model,
            num_layers=config.num_layers,
            num_heads=config.attention_heads,
            num_kv_heads=config.attention_heads,
            head_dim=d,
            intermediate_size=config.ffn_dim,
            rms_norm_eps=config.layer_norm_eps,
            norm_type="layer",
            norm_bias=True,
            activation=config.activation_function,
            mlp_kind="plain",
            mlp_bias=True,
            attention_bias=True,
            o_bias=True,
            learned_pos=True,                # fixed sinusoidal table, same path
            pos_offset=2,                    # fairseq offset
            embedding_multiplier=(math.sqrt(config.d_model)
                                  if config.scale_embedding else 1.0),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.d_model // config.attention_heads
        return np.zeros((d // 2,), np.float32)   # positions are sinusoidal, no rope

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv",
                                  "bq", "bk", "bv", "wo", "bo",
                                  "ln2", "ln2_b", "wg", "bg", "wd", "bd")}
        for i in range(config.num_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.out_proj.weight"))
            layers["bo"].append(get(p + "self_attn.out_proj.bias"))
            layers["ln1"].append(get(p + "self_attn_layer_norm.weight"))
            layers["ln1_b"].append(get(p + "self_attn_layer_norm.bias"))
            layers["ln2"].append(get(p + "final_layer_norm.weight"))
            layers["ln2_b"].append(get(p + "final_layer_norm.bias"))
            layers["wg"].append(lin_t(p + "fc1.weight"))
            layers["bg"].append(get(p + "fc1.bias"))
            layers["wd"].append(lin_t(p + "fc2.weight"))
            layers["bd"].append(get(p + "fc2.bias"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "pos_embed": sinusoidal_table(
                config.max_position_embeddings + 2, config.d_model),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.layer_norm.weight"),
            "final_norm_b": get("model.layer_norm.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
