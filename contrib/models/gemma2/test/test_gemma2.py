"""gemma2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gemma2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_gemma2_parity():
    from transformers import Gemma2Config, Gemma2ForCausalLM as HFGemma2

    from contrib.models.gemma2.src.modeling_gemma2 import Gemma2ForCausalLM

    cfg = Gemma2Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_hidden_layers=4, num_attention_heads=4,
                       num_key_value_heads=2, head_dim=16,
                       query_pre_attn_scalar=16.0,
                       attn_logit_softcapping=30.0, final_logit_softcapping=20.0,
                       sliding_window=16)
    torch.manual_seed(0)
    hf = HFGemma2(cfg).eval()
    _run_parity(Gemma2ForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
