"""Gemma 2 on the TPU framework (contrib port).

≈ reference gemma-2 contrib. The Gemma-2 block combines sandwich norms
(post-attention + pre/post-feedforward), alternating sliding/full attention
(layer_pattern with rolling sliding caches), attention logit soft-capping, a
final-logit soft cap, query_pre_attn_scalar attention scaling, zero-centered
(1+w) RMSNorms, sqrt(hidden) embedding scaling, and tied embeddings. The
soft-cap rides the Pallas kernels (ops/flash_attention.py).
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class Gemma2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size", "head_dim")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("hidden_activation", "gelu_pytorch_tanh"),
                              ("query_pre_attn_scalar", 256.0),
                              ("attn_logit_softcapping", 50.0),
                              ("final_logit_softcapping", 30.0),
                              ("sliding_window", 4096)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)

    def layer_pattern(self):
        # HF Gemma2Attention: sliding on EVEN layer indices, full on odd
        if getattr(self, "layer_types", None):
            return tuple("sliding" if t == "sliding_attention" else "full"
                         for t in self.layer_types)
        return tuple("sliding" if i % 2 == 0 else "full"
                     for i in range(self.num_hidden_layers))


class Gemma2ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return Gemma2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_activation,
            zero_centered_norms=True,
            sandwich_norms=True,
            sliding_window=int(config.sliding_window),
            layer_pattern=config.layer_pattern(),
            attention_scale=float(config.query_pre_attn_scalar) ** -0.5,
            logits_soft_cap=float(config.attn_logit_softcapping),
            final_logits_soft_cap=float(config.final_logit_softcapping),
            embedding_multiplier=float(config.hidden_size) ** 0.5,
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim,
                                         float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        # sandwich norms are absent in the VaultGemma subclass's checkpoints
        sandwich = "model.layers.0.post_attention_layernorm.weight" in state_dict
        keys = ["ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd"]
        if sandwich:
            keys += ["ln1_post", "ln2_post"]
        layers = {k: [] for k in keys}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "pre_feedforward_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
            if sandwich:
                layers["ln1_post"].append(
                    get(p + "post_attention_layernorm.weight"))
                layers["ln2_post"].append(
                    get(p + "post_feedforward_layernorm.weight"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
