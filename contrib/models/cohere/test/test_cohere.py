"""cohere parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/cohere/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_cohere_parity():
    from transformers import CohereConfig, CohereForCausalLM as HFCohere

    from contrib.models.cohere.src.modeling_cohere import CohereForCausalLM

    cfg = CohereConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, logit_scale=0.25,
                       use_qk_norm=False, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFCohere(cfg).eval()
    _run_parity(CohereForCausalLM, hf, cfg)
