"""Cohere Command-R on the TPU framework (contrib port).

≈ reference `contrib/models/c4ai-command-r7b-12-2024/` (v1 architecture). The
Command-R block is a single-LayerNorm parallel-residual layer
(h = x + attn(LN(x)) + mlp(LN(x))), interleaved-pair rotary, and logits
multiplied by logit_scale; embeddings are tied.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class CohereInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("layer_norm_eps", 1e-5),
                              ("logit_scale", 1.0), ("use_qk_norm", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class CohereForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return CohereInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        if getattr(config, "use_qk_norm", False):
            raise ValueError("Cohere use_qk_norm (per-head LayerNorm) is not "
                             "ported yet")
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            norm_type="layer",
            parallel_residual=True,
            shared_ln=True,
            rope_interleaved=True,
            logits_scale=float(config.logit_scale),
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            ln = get(p + "input_layernorm.weight")
            layers["ln1"].append(ln)
            layers["ln2"].append(np.ones_like(ln))   # unused under shared_ln
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
