"""IDEFICS (Flamingo-style gated cross-attention VLM) on the TPU framework
(contrib port).

≈ reference `contrib/models/idefics-9b-instruct/`. Unlike the projector VLMs,
IDEFICS conditions a llama-shaped LM on images through GATED CROSS-ATTENTION
blocks inserted before every ``cross_layer_interval``-th decoder layer:
h += tanh(alpha_cross)·cross_attn(ln(h), img); h += tanh(alpha_dense)·mlp(ln(h)),
with rows attending no image hard-zeroed (cross_attention_gate). Vision side:
a CLIP tower (shared ops/vit.py) optionally compressed by the PERCEIVER
RESAMPLER (latents cross-attending [context; latents], stable softmax).
TPU design mirrors the mllama family: cross k/v are computed once at prefill
and ride the cache pytree; the decode visibility row (last prompt token's
image_attention_mask) rides along as ``xmask_dec``. Extras vs llama: no GQA,
optional POST-rope per-head q/k RMSNorm, decoupled embeddings/lm_head
(additional vocab rows concatenated at conversion).
"""

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import layer_norm, rms_norm
from neuronx_distributed_inference_tpu.ops.vit import ViTSpec, vit_encode
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class IdeficsArchArgs(ModelArchArgs):
    cross_layer_interval: int = 1
    vision_tokens: int = 0          # num_images * tokens_per_image (static)
    qk_layer_norms: bool = False


# --- vision: CLIP tower + optional perceiver resampler ---------------------------


def idefics_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                          patch_size: int, num_heads: int, eps: float,
                          resampler: bool, perceiver_heads: int,
                          perceiver_qk_norms: bool) -> jnp.ndarray:
    """(N_img, C, H, W) -> (N_img, T_img, H_vis) image hidden states."""
    # HF IdeficsVisionTransformer post-norms only the pooled CLS output; the
    # last_hidden_state fed to the perceiver/cross-attention is UN-normed
    spec = ViTSpec(patch_size=patch_size, num_heads=num_heads, eps=eps,
                   act="gelu", patch_bias=False, cls_token=True, pre_ln=True,
                   post_ln=False)
    h = vit_encode(vp, pixel_values, spec)          # (N, 1+T, H_vis) incl CLS
    if not resampler:
        return h

    pp = vp["perceiver"]
    n = h.shape[0]
    latents = jnp.broadcast_to(pp["latents"][None], (n,) + pp["latents"].shape)

    def block(lat, lp):
        ctx = layer_norm(h, lp["ctx_ln"], lp["ctx_ln_b"], eps=1e-5)
        ql = layer_norm(lat, lp["lat_ln"], lp["lat_ln_b"], eps=1e-5)
        kv_in = jnp.concatenate([ctx, ql], axis=1)
        d = lp["wq"].shape[1] // perceiver_heads
        b, s_l, _ = ql.shape
        s_kv = kv_in.shape[1]
        q = (ql @ lp["wq"]).reshape(b, s_l, perceiver_heads, d
                                    ).transpose(0, 2, 1, 3)
        k = (kv_in @ lp["wk"]).reshape(b, s_kv, perceiver_heads, d
                                       ).transpose(0, 2, 1, 3)
        v = (kv_in @ lp["wv"]).reshape(b, s_kv, perceiver_heads, d
                                       ).transpose(0, 2, 1, 3)
        if perceiver_qk_norms:
            q = layer_norm(q, lp["q_ln"], lp["q_ln_b"], eps=1e-5)
            k = layer_norm(k, lp["k_ln"], lp["k_ln_b"], eps=1e-5)
        a = attend(q, k, v)
        a = a.transpose(0, 2, 1, 3).reshape(b, s_l, -1)
        lat = lat + a @ lp["wo"]
        x = layer_norm(lat, lp["mlp_ln"], lp["mlp_ln_b"], eps=1e-5)
        lat = lat + jax.nn.relu(x @ lp["fc"]) @ lp["c_proj"]
        return lat, None

    latents, _ = jax.lax.scan(block, latents, pp["blocks"])
    return layer_norm(latents, pp["out_ln"], pp["out_ln_b"], eps=1e-5)


# --- text stack -------------------------------------------------------------------


def _qk_head_norm(lp, args, q, k):
    q = rms_norm(q, lp["q_ln"], args.rms_norm_eps)
    k = rms_norm(k, lp["k_ln"], args.rms_norm_eps)
    return q, k


def _self_layer(lp, args: IdeficsArchArgs, h, cos, sin, mask, k_cache, v_cache,
                positions, bucket):
    b, t, _ = h.shape
    n, d = args.num_heads, args.head_dim
    hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
    q = (hn @ lp["wq"]).reshape(b, t, n, d).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, t, n, d).transpose(0, 2, 1, 3)
    v = (hn @ lp["wv"]).reshape(b, t, n, d).transpose(0, 2, 1, 3)
    q, k = rope_ops.apply_rotary(q, k, cos, sin)
    # NOTE: config.qk_layer_norms applies to the CROSS attention only — HF's
    # IdeficsDecoderLayer builds its self-attention without them
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, n * d)
    h = h + attn @ lp["wo"]
    hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
    h = h + (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
    return h, k_cache, v_cache


def _cross_block(lp, args: IdeficsArchArgs, h, xk, xv, xmask, xgate):
    """xk/xv (B, H, T_vis, D) precomputed image KV; xmask (B, S, T_vis) bool;
    xgate (B, S, 1) float zeroing rows that attend no image."""
    b, t, _ = h.shape
    n, d = args.num_heads, args.head_dim
    hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
    q = (hn @ lp["wq"]).reshape(b, t, n, d).transpose(0, 2, 1, 3)
    k, v = xk.astype(q.dtype), xv.astype(q.dtype)
    if args.qk_layer_norms:
        q, k = _qk_head_norm(lp, args, q, k)
    # a fully-masked row would softmax over -inf only; give it one fake slot
    # (the xgate zero erases its output)
    safe_mask = jnp.logical_or(xmask, ~xmask.any(-1, keepdims=True))
    attn = attend(q, k, v, mask=safe_mask[:, None])
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, n * d)
    attn = (attn @ lp["wo"]) * xgate.astype(h.dtype)
    h = h + jnp.tanh(lp["alpha_cross"]) * attn
    hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
    mlp = (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
    return h + jnp.tanh(lp["alpha_dense"]) * mlp


def _compute_cross_kv(params, args: IdeficsArchArgs, image_states):
    """image_states (B, T_vis, H_vis) -> per-cross-layer (B, H, T_vis, D)."""
    b, tv, _ = image_states.shape
    n, d = args.num_heads, args.head_dim
    xks, xvs = [], []
    for lp in params["cross_layers"]:
        xk = (image_states @ lp["wk"]).reshape(b, tv, n, d).transpose(0, 2, 1, 3)
        xv = (image_states @ lp["wv"]).reshape(b, tv, n, d).transpose(0, 2, 1, 3)
        xks.append(xk)
        xvs.append(xv)
    return jnp.stack(xks), jnp.stack(xvs)


def _run_stack(params, args: IdeficsArchArgs, h, cos, sin, mask, cache,
               xmask, xgate, positions, bucket):
    ks, vs = [], []
    xi = 0
    for i in range(args.num_layers):
        if i % args.cross_layer_interval == 0:
            h = _cross_block(params["cross_layers"][i // args.cross_layer_interval],
                             args, h, cache["xk"][xi], cache["xv"][xi],
                             xmask, xgate)
            xi += 1
        lp = {k: v[i] for k, v in params["layers"].items()}  # stacked arrays
        h, kc, vc = _self_layer(lp, args, h, cos, sin, mask, cache["k"][i],
                                cache["v"][i], positions, bucket)
        ks.append(kc)
        vs.append(vc)
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out = {"k": jnp.stack(ks), "v": jnp.stack(vs), "xk": cache["xk"],
           "xv": cache["xv"], "xmask_dec": cache["xmask_dec"]}
    return h, out


def _logits(params, h):
    out = h @ params["lm_head"]
    if "lm_head_extra" in params:
        out = jnp.concatenate([out, h @ params["lm_head_extra"]], axis=-1)
    return out.astype(jnp.float32)


def prefill_forward(params, args: IdeficsArchArgs, input_ids, position_ids,
                    last_token_idx, cache, image_states, xmask, xmask_dec,
                    mesh=None, rules=None, **_ignored):
    h = jnp.take(params["embed"], input_ids, axis=0)
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    xk, xv = _compute_cross_kv(params, args, image_states)
    cache = dict(cache, xk=xk, xv=xv, xmask_dec=xmask_dec)
    xgate = xmask.any(-1, keepdims=True).astype(jnp.float32)
    h, out_cache = _run_stack(params, args, h, cos, sin, mask, cache,
                              xmask, xgate, None, None)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    return _logits(params, h_last), out_cache


def decode_forward(params, args: IdeficsArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None, tree=None,
                   **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("idefics decode is single-token only in this port")
    h = jnp.take(params["embed"], input_ids, axis=0)
    pos_grid = position_ids[:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= pos_grid[:, None, :, None]
    xmask = cache["xmask_dec"][:, None, :]                     # (B, 1, T_vis)
    xgate = xmask.any(-1, keepdims=True).astype(jnp.float32)
    h, out_cache = _run_stack(params, args, h, cos, sin, mask, cache,
                              xmask, xgate, position_ids, decode_bucket)
    return _logits(params, h), out_cache


# --- application ------------------------------------------------------------------


class IdeficsInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size", "vision_config")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("cross_layer_interval", 1),
                              ("qk_layer_norms", False),
                              ("additional_vocab_size", 0),
                              ("max_num_images", 1),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not isinstance(self.vision_config, dict):
            self.vision_config = self.vision_config.to_dict()
        if hasattr(self, "perceiver_config") \
                and not isinstance(self.perceiver_config, dict):
            self.perceiver_config = self.perceiver_config.to_dict()
        if not hasattr(self, "perceiver_config"):
            self.perceiver_config = {}
        if hasattr(self, "use_resampler"):
            self.perceiver_config["use_resampler"] = bool(self.use_resampler)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads

    def tokens_per_image(self) -> int:
        pc = self.perceiver_config
        if pc.get("use_resampler"):
            return int(pc["resampler_n_latents"])
        vc = self.vision_config
        return (vc["image_size"] // vc["patch_size"]) ** 2 + 1   # incl CLS


class IdeficsForVisionText2Text(TpuModelForCausalLM):
    """≈ HF IdeficsForVisionText2Text."""

    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "IDEFICS")
        super().__init__(model_path, config, mesh=mesh)
        self.vision_params = None
        vc = config.vision_config
        pc = config.perceiver_config
        self._encode_fn = functools.partial(
            idefics_vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            eps=vc.get("layer_norm_eps", 1e-5),
            resampler=bool(pc.get("use_resampler")),
            perceiver_heads=int(pc.get("resampler_n_heads", 1)),
            perceiver_qk_norms=bool(pc.get("qk_layer_norms_perceiver")),
        )
        self._xprefill_step = jax.jit(self._make_xprefill(), donate_argnums=(5,))

    @classmethod
    def get_config_cls(cls):
        return IdeficsInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> IdeficsArchArgs:
        return IdeficsArchArgs(
            vocab_size=config.vocab_size + config.additional_vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_attention_heads,   # no GQA in idefics
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            cross_layer_interval=int(config.cross_layer_interval),
            vision_tokens=int(config.max_num_images)
            * config.tokens_per_image(),
            qk_layer_norms=bool(config.qk_layer_norms),
        )

    def prefill_fn(self):
        a = self.arch_args

        def _text_only(params, args, input_ids, position_ids, last_token_idx,
                       cache, mesh=None, rules=None, **_):
            b, s = input_ids.shape
            vc = self.config.vision_config
            h_vis = vc["embed_dim"]
            zeros = jnp.zeros((b, a.vision_tokens, h_vis),
                              dtype=self.tpu_config.jax_dtype)
            xmask = jnp.zeros((b, s, a.vision_tokens), dtype=bool)
            xmask_dec = jnp.zeros((b, a.vision_tokens), dtype=bool)
            return prefill_forward(params, args, input_ids, position_ids,
                                   last_token_idx, cache, zeros, xmask,
                                   xmask_dec, mesh=mesh, rules=rules)

        return _text_only

    def decode_fn(self):
        return decode_forward

    def _make_xprefill(self):
        args = self.arch_args
        odsc = self.sampling_config
        from neuronx_distributed_inference_tpu.ops import sampling as sampling_ops

        precision = ("highest" if self.tpu_config.dtype == "float32"
                     else "default")

        def _prefill_mm(params, vision_params, input_ids, position_ids,
                        last_token_idx, cache, sampling_params, key,
                        pixel_values, xmask, xmask_dec):
            with jax.default_matmul_precision(precision):
                b = input_ids.shape[0]
                n_img = pixel_values.shape[1]
                flat = pixel_values.reshape((b * n_img,) + pixel_values.shape[2:])
                img = self._encode_fn(vision_params, flat)
                img = img.reshape(b, -1, img.shape[-1])    # (B, T_vis, H_vis)
                logits, cache = prefill_forward(
                    params, args, input_ids, position_ids, last_token_idx,
                    cache, img.astype(self.tpu_config.jax_dtype), xmask,
                    xmask_dec)
                tokens = sampling_ops.sample(logits, sampling_params, key, odsc)
            return tokens, logits, cache

        return _prefill_mm

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim,
                                         float(config.rope_theta))

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: IdeficsArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        n_cross = (a.num_layers + a.cross_layer_interval - 1) \
            // a.cross_layer_interval
        self.kv_cache = {
            "k": jnp.zeros((a.num_layers, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((a.num_layers, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "xk": jnp.zeros((n_cross, b, a.num_heads, a.vision_tokens,
                             a.head_dim), dt),
            "xv": jnp.zeros((n_cross, b, a.num_heads, a.vision_tokens,
                             a.head_dim), dt),
            "xmask_dec": jnp.zeros((b, a.vision_tokens), dtype=bool),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        params = jax.tree.map(_put, host_params)
        params["rope_inv_freq"] = jax.device_put(
            np.asarray(host_params["rope_inv_freq"], np.float32))
        self.params = params
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    def _post_load_state_dict(self, state_dict) -> None:
        host = self.convert_hf_vision_state_dict(state_dict, self.config)
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f" or arr.dtype.name == "bfloat16":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        self.vision_params = jax.tree.map(_put, host)

    load_vision_from_state_dict = _post_load_state_dict

    # --- generate ------------------------------------------------------------------
    def generate(self, input_ids, pixel_values=None, image_attention_mask=None,
                 **kwargs):
        """pixel_values (B, num_images, C, H, W); image_attention_mask
        (B, S, num_images) 0/1 per HF processor (default: attend all)."""
        if pixel_values is None:
            return super().generate(input_ids, **kwargs)
        pixel_values = np.asarray(pixel_values, dtype=np.float32)
        b, s = np.asarray(input_ids).shape
        n_img = pixel_values.shape[1]
        m_max = int(self.config.max_num_images)
        if n_img > m_max:
            raise ValueError(
                f"request carries {n_img} images but the graph was compiled "
                f"for max_num_images={m_max}; raise config.max_num_images")
        if image_attention_mask is None:
            image_attention_mask = np.ones((b, s, n_img), dtype=np.int32)
        iam = np.asarray(image_attention_mask, dtype=np.int32)
        if n_img < m_max:   # pad the image axis to the compiled static shape
            pad_n = m_max - n_img
            pixel_values = np.concatenate(
                [pixel_values, np.zeros((pixel_values.shape[0], pad_n)
                                        + pixel_values.shape[2:],
                                        pixel_values.dtype)], axis=1)
            iam = np.concatenate(
                [iam, np.zeros(iam.shape[:2] + (pad_n,), iam.dtype)], axis=2)
        mm = {"pixel_values": pixel_values, "image_attention_mask": iam}
        return super().generate(input_ids, _mm_embeds=mm, **kwargs)

    def _run_prefill(self, padded, sampling_params, key, adapter_ids, mm=None):
        if mm is None:
            return super()._run_prefill(padded, sampling_params, key,
                                        adapter_ids)
        a: IdeficsArchArgs = self.arch_args
        b, s = padded.input_ids.shape
        tpi = self.config.tokens_per_image()
        iam = mm["image_attention_mask"]                 # (B_in, S_in, n_img)
        allowed = np.repeat(iam, tpi, axis=2).astype(bool)
        xmask = np.zeros((b, s, a.vision_tokens), dtype=bool)
        s_in = min(allowed.shape[1], s)
        xmask[:allowed.shape[0], :s_in, :allowed.shape[2]] = allowed[:, :s_in]
        last = np.asarray(padded.last_token_idx)
        xmask_dec = xmask[np.arange(b), np.minimum(last, s - 1)]
        pix = mm["pixel_values"]
        if pix.shape[0] < b:
            pad = np.zeros((b - pix.shape[0],) + pix.shape[1:], pix.dtype)
            pix = np.concatenate([pix, pad], axis=0)
        return self._xprefill_step(
            self.params, self.vision_params, padded.input_ids,
            padded.position_ids, padded.last_token_idx, self.kv_cache,
            sampling_params, key, pix, xmask, xmask_dec)

    # --- conversion ----------------------------------------------------------------
    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        qk = bool(config.qk_layer_norms)   # cross-attention layers only
        self_keys = ["ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd"]
        layers = {k: [] for k in self_keys}
        cross = []
        interval = int(config.cross_layer_interval)
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
            if i % interval == 0:
                g = f"model.gated_cross_attn_layers.{i // interval}."
                clp = {
                    "ln1": get(g + "input_layernorm.weight"),
                    "wq": lin_t(g + "cross_attn.q_proj.weight"),
                    "wk": lin_t(g + "cross_attn.k_proj.weight"),
                    "wv": lin_t(g + "cross_attn.v_proj.weight"),
                    "wo": lin_t(g + "cross_attn.o_proj.weight"),
                    "ln2": get(g + "post_attention_layernorm.weight"),
                    "wg": lin_t(g + "mlp.gate_proj.weight"),
                    "wu": lin_t(g + "mlp.up_proj.weight"),
                    "wd": lin_t(g + "mlp.down_proj.weight"),
                    "alpha_cross": get(g + "alpha_cross_attn").reshape(-1),
                    "alpha_dense": get(g + "alpha_dense").reshape(-1),
                }
                if qk:
                    clp["q_ln"] = get(g + "cross_attn.q_layer_norm.weight")
                    clp["k_ln"] = get(g + "cross_attn.k_layer_norm.weight")
                cross.append(clp)

        embed = get("model.embed_tokens.weight")
        if "model.embed_tokens.additional_embedding.weight" in state_dict:
            embed = np.concatenate(
                [embed, get("model.embed_tokens.additional_embedding.weight")],
                axis=0)
        lm_head = lin_t("lm_head.weight")
        out = {
            "embed": embed,
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "cross_layers": cross,
            "final_norm": get("model.norm.weight"),
            "lm_head": lm_head,
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if "lm_head.additional_fc.weight" in state_dict:
            out["lm_head_extra"] = lin_t("lm_head.additional_fc.weight")
        return out

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        vc = config.vision_config
        pc = config.perceiver_config
        hidden = vc["embed_dim"]

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ("ln1", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
                "ln2", "ln2_b", "w1", "b1", "w2", "b2")
        layers = {k: [] for k in keys}
        for i in range(vc["num_hidden_layers"]):
            p = f"model.vision_model.encoder.layers.{i}."
            layers["ln1"].append(get(p + "layer_norm1.weight"))
            layers["ln1_b"].append(get(p + "layer_norm1.bias"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.out_proj.weight"))
            layers["bo"].append(get(p + "self_attn.out_proj.bias"))
            layers["ln2"].append(get(p + "layer_norm2.weight"))
            layers["ln2_b"].append(get(p + "layer_norm2.bias"))
            layers["w1"].append(lin_t(p + "mlp.fc1.weight"))
            layers["b1"].append(get(p + "mlp.fc1.bias"))
            layers["w2"].append(lin_t(p + "mlp.fc2.weight"))
            layers["b2"].append(get(p + "mlp.fc2.bias"))

        emb = "model.vision_model.embeddings."
        conv = get(emb + "patch_embedding.weight")
        vp = {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "cls": get(emb + "class_embedding"),
            "pos_embed": get(emb + "position_embedding.weight"),
            "ln_pre": get("model.vision_model.pre_layrnorm.weight"),
            "ln_pre_b": get("model.vision_model.pre_layrnorm.bias"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            # post_layernorm only norms HF's pooled CLS output — unused here
        }
        if pc.get("use_resampler"):
            pr = "model.perceiver_resampler."
            blocks = {k: [] for k in ("ctx_ln", "ctx_ln_b", "lat_ln",
                                      "lat_ln_b", "wq", "wk", "wv", "wo",
                                      "mlp_ln", "mlp_ln_b", "fc", "c_proj",
                                      "q_ln", "q_ln_b", "k_ln", "k_ln_b")}
            qk = bool(pc.get("qk_layer_norms_perceiver"))
            for i in range(int(pc["resampler_depth"])):
                bp = pr + f"blocks.{i}."
                blocks["ctx_ln"].append(get(bp + "0.context_layer_norm.weight"))
                blocks["ctx_ln_b"].append(get(bp + "0.context_layer_norm.bias"))
                blocks["lat_ln"].append(get(bp + "0.latents_layer_norm.weight"))
                blocks["lat_ln_b"].append(get(bp + "0.latents_layer_norm.bias"))
                blocks["wq"].append(lin_t(bp + "0.q_proj.weight"))
                blocks["wk"].append(lin_t(bp + "0.k_proj.weight"))
                blocks["wv"].append(lin_t(bp + "0.v_proj.weight"))
                blocks["wo"].append(lin_t(bp + "0.output_proj.weight"))
                if qk:
                    blocks["q_ln"].append(get(bp + "0.q_layer_norm.weight"))
                    blocks["q_ln_b"].append(get(bp + "0.q_layer_norm.bias"))
                    blocks["k_ln"].append(get(bp + "0.k_layer_norm.weight"))
                    blocks["k_ln_b"].append(get(bp + "0.k_layer_norm.bias"))
                blocks["mlp_ln"].append(get(bp + "1.ln.weight"))
                blocks["mlp_ln_b"].append(get(bp + "1.ln.bias"))
                blocks["fc"].append(lin_t(bp + "1.fc.weight"))
                blocks["c_proj"].append(lin_t(bp + "1.c_proj.weight"))
            if not qk:
                for k in ("q_ln", "q_ln_b", "k_ln", "k_ln_b"):
                    del blocks[k]
            vp["perceiver"] = {
                "latents": get(pr + "latents"),
                "blocks": {k: np.stack(v) for k, v in blocks.items()},
                "out_ln": get(pr + "layer_norm.weight"),
                "out_ln_b": get(pr + "layer_norm.bias"),
            }
        return vp
