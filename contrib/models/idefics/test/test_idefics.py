"""idefics parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/idefics/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_idefics_generate_matches_hf():
    """IDEFICS gated cross-attention: perceiver-resampled CLIP features, cross
    blocks every 2 layers with tanh-alpha gates, post-rope per-head qk norms,
    decoupled embeddings/lm_head (2 additional vocab rows)."""
    from transformers import IdeficsConfig, IdeficsForVisionText2Text as HFIdefics

    from contrib.models.idefics.src.modeling_idefics import (
        IdeficsForVisionText2Text)

    cfg = IdeficsConfig(
        vocab_size=256, additional_vocab_size=2, hidden_size=32,
        intermediate_size=64, num_hidden_layers=4, num_attention_heads=4,
        cross_layer_interval=2, qk_layer_norms=True, rms_norm_eps=1e-5,
        tie_word_embeddings=False, pad_token_id=0, bos_token_id=1,
        eos_token_id=2, freeze_text_layers=False, freeze_vision_layers=False,
        vision_config={"embed_dim": 24, "image_size": 16, "patch_size": 8,
                       "num_hidden_layers": 2, "num_attention_heads": 2,
                       "intermediate_size": 48, "hidden_act": "gelu",
                       "num_channels": 3},
        perceiver_config={"use_resampler": True, "resampler_n_latents": 4,
                          "resampler_depth": 2, "resampler_n_heads": 2,
                          "resampler_head_dim": 12,
                          "qk_layer_norms_perceiver": True},
    )
    torch.manual_seed(0)
    hf = HFIdefics(cfg).eval()
    with torch.no_grad():   # HF post-norms only the pooled CLS; must be unused
        hf.model.vision_model.post_layernorm.weight.copy_(torch.randn(24))
        hf.model.vision_model.post_layernorm.bias.copy_(torch.randn(24))

    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = IdeficsForVisionText2Text.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(
            dict(cfg.to_dict(), max_num_images=2)))
    app = IdeficsForVisionText2Text(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(1)
    ids = rng.integers(3, 258, size=(2, 12))    # incl additional-vocab ids
    pixels = rng.normal(size=(2, 1, 3, 16, 16)).astype(np.float32)
    out = app.generate(ids, pixel_values=pixels, max_new_tokens=6,
                       eos_token_id=-1)

    # HF full-recompute greedy oracle (attend-all image mask each step)
    cur = torch.tensor(ids)
    for _ in range(6):
        iam = torch.ones((2, cur.shape[1], 1), dtype=torch.long)
        with torch.no_grad():
            logits = hf(input_ids=cur, pixel_values=torch.tensor(pixels),
                        image_attention_mask=iam).logits
        cur = torch.cat([cur, logits[:, -1].argmax(-1)[:, None]], 1)
    np.testing.assert_array_equal(out.tokens, cur[:, 12:].numpy())

    # text-only path still serves (zero image states, fully-masked cross rows)
    tids = rng.integers(3, 250, size=(2, 10)).astype(np.int64)
    out_t = app.generate(tids, max_new_tokens=4, eos_token_id=-1)
    cur = torch.tensor(tids)
    for _ in range(4):
        iam = torch.zeros((2, cur.shape[1], 1), dtype=torch.long)
        with torch.no_grad():
            logits = hf(input_ids=cur,
                        pixel_values=torch.zeros(2, 1, 3, 16, 16),
                        image_attention_mask=iam).logits
        cur = torch.cat([cur, logits[:, -1].argmax(-1)[:, None]], 1)
    np.testing.assert_array_equal(out_t.tokens, cur[:, 10:].numpy())
