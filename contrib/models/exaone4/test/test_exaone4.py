"""exaone4 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/exaone4/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_exaone4_parity():
    from transformers import Exaone4Config, Exaone4ForCausalLM as HFExaone4

    from contrib.models.exaone4.src.modeling_exaone4 import Exaone4ForCausalLM

    cfg = Exaone4Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=4, num_attention_heads=4,
                        num_key_value_heads=2, sliding_window=16,
                        layer_types=["sliding_attention", "sliding_attention",
                                     "sliding_attention", "full_attention"],
                        pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFExaone4(cfg).eval()
    _run_parity(Exaone4ForCausalLM, hf, cfg)
