"""zamba2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/zamba2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_zamba2_parity():
    """Zamba2: mamba2 backbone with ONE shared transformer block invoked at
    hybrid positions on concat(h, h0), per-invocation MLP LoRA adapters, and
    a per-layer linear feeding the block output into the mamba input."""
    from transformers import Zamba2Config, Zamba2ForCausalLM as HFZamba2

    from contrib.models.zamba2.src.modeling_zamba2 import Zamba2ForCausalLM

    cfg = Zamba2Config(vocab_size=256, hidden_size=32, num_hidden_layers=4,
                       hybrid_layer_ids=[1, 3],
                       layers_block_type=["mamba", "hybrid", "mamba",
                                          "hybrid"],
                       num_attention_heads=4, num_key_value_heads=4,
                       attention_head_dim=16, intermediate_size=64,
                       num_mem_blocks=1, adapter_rank=4, mamba_d_state=8,
                       mamba_d_conv=4, mamba_expand=2, n_mamba_heads=4,
                       mamba_headdim=16, mamba_ngroups=2, use_mem_rope=True,
                       use_shared_attention_adapter=False,
                       max_position_embeddings=128, pad_token_id=0,
                       tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFZamba2(cfg).eval()
    _run_parity(Zamba2ForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
