"""Zamba2 (Zyphra shared-block hybrid) on the TPU framework (contrib port).

≈ reference contrib hybrid family. Every layer runs a mamba2 SSD mixer; at
the ``hybrid_layer_ids`` positions ONE shared transformer block (attention +
gated-gelu MLP, weights tied across all invocations) first processes
concat(h, h0) — h0 being the embedding output — with per-invocation LoRA
adapters on the MLP's gate_up projection restoring expressivity, and its
output rides a per-layer linear into the mamba input (Zamba2 paper eq. 6;
HF `Zamba2HybridLayer`). Attention spans the doubled width (scale
(head_dim/2)^-0.5) and is rope-free unless ``use_mem_rope``; a zero
inv-freq table makes the rotation an identity when disabled. The mixer math
(with Zamba2's grouped gated norm, eps 1e-5) comes from contrib/models/mamba2.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from contrib.models.mamba2.src.modeling_mamba2 import (Mamba2ArchArgs,
                                                       _mixer_decode,
                                                       _mixer_prefill)
from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class Zamba2ArchArgs(Mamba2ArchArgs):
    layer_kinds: Tuple[str, ...] = ()


def _shared_block(params, hi, h, h0, cos, sin, mask, k_cache, v_cache,
                  positions, bucket, args):
    """One invocation of the tied transformer block at hybrid index ``hi``:
    concat(h, h0) → ln → attention (2H wide) → ln → MLP+LoRA → per-layer
    linear. No residuals inside (HF `Zamba2AttentionDecoderLayer`)."""
    sp = params["shared"]
    b, t, _ = h.shape
    x = jnp.concatenate([h, h0], axis=-1)
    xn = rms_norm(x, sp["ln1"], args.rms_norm_eps)
    q = (xn @ sp["wq"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (xn @ sp["wk"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    v = (xn @ sp["wv"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    q, k = rope_ops.apply_rotary(q, k, cos, sin)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    a = attend(q, k_att, v_att, mask=mask, scale=(args.head_dim / 2) ** -0.5)
    a = a.transpose(0, 2, 1, 3).reshape(b, t, -1) @ sp["wo"]

    hn = rms_norm(a, sp["ln2"], args.rms_norm_eps)
    gu = hn @ sp["gate_up"] + (hn @ params["adapter_a"][hi]
                               ) @ params["adapter_b"][hi]
    gate, up = jnp.split(gu, 2, axis=-1)
    mlp = (jax.nn.gelu(gate, approximate=False) * up) @ sp["down"]
    return mlp @ params["linear"][hi], k_cache, v_cache


def _forward(params, args: Zamba2ArchArgs, h, cos, sin, mask, cache, positions,
             bucket, last_token_idx):
    h0 = h
    ks, vs, convs, ssms = [], [], [], []
    hi = 0
    for li, kind in enumerate(args.layer_kinds):
        lp = params["layers"][li]
        if kind == "hybrid":
            t_states, kc, vc = _shared_block(
                params, hi, h, h0, cos, sin, mask, cache["k"][hi],
                cache["v"][hi], positions, bucket, args)
            ks.append(kc)
            vs.append(vc)
            hi += 1
        else:
            t_states = 0.0
        resid = h
        hn = rms_norm(h + t_states, lp["ln1"], args.rms_norm_eps)
        if positions is None:
            out, conv_state, ssm_state = _mixer_prefill(lp, hn, last_token_idx,
                                                        args)
        else:
            out, conv_state, ssm_state = _mixer_decode(
                lp, hn, cache["conv"][li], cache["ssm"][li], args)
        convs.append(conv_state)
        ssms.append(ssm_state)
        h = resid + out
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks) if ks else cache["k"],
                 "v": jnp.stack(vs) if vs else cache["v"],
                 "conv": jnp.stack(convs), "ssm": jnp.stack(ssms)}
    return h, out_cache


def prefill_forward(params, args: Zamba2ArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h_last @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: Zamba2ArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Zamba2 decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"],
                                        position_ids[:, None])
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= position_ids[:, None, None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache,
                            position_ids, decode_bucket, None)
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class Zamba2InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size", "n_mamba_heads",
                           "mamba_d_state", "hybrid_layer_ids")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("mamba_d_conv", 4), ("mamba_expand", 2),
                              ("mamba_ngroups", 1), ("adapter_rank", 128),
                              ("use_mem_rope", False),
                              ("num_mem_blocks", 1),
                              ("use_shared_attention_adapter", False),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "attention_head_dim") or \
                self.attention_head_dim is None:
            self.attention_head_dim = (2 * self.hidden_size
                                       // self.num_attention_heads)
        if not getattr(self, "layers_block_type", None):
            hyb = set(self.hybrid_layer_ids)
            self.layers_block_type = ["hybrid" if i in hyb else "mamba"
                                      for i in range(self.num_hidden_layers)]
        if int(self.num_mem_blocks) != 1:
            raise ValueError("Zamba2 num_mem_blocks > 1 is not ported "
                             "(released checkpoints use one shared block)")
        if getattr(self, "use_shared_attention_adapter", False):
            raise ValueError("Zamba2 use_shared_attention_adapter=True is "
                             "not ported")
        if getattr(self, "add_bias_linear", False):
            raise ValueError("Zamba2 add_bias_linear=True is not ported")
        if getattr(self, "hidden_act", "gelu") != "gelu":
            raise ValueError(f"Zamba2 hidden_act={self.hidden_act!r} is not "
                             "ported (shared block uses exact gelu)")
        kvh = getattr(self, "num_key_value_heads", None)
        if kvh is not None and kvh != self.num_attention_heads:
            raise ValueError("Zamba2 GQA (num_key_value_heads < "
                             "num_attention_heads) is not ported")


class Zamba2ForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config,
                                  "Zamba2 (shared-block hybrid)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return Zamba2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> Zamba2ArchArgs:
        d_inner = int(config.mamba_expand * config.hidden_size)
        return Zamba2ArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_attention_heads,
            head_dim=int(config.attention_head_dim),
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            d_inner=d_inner,
            d_state=int(config.mamba_d_state),
            d_conv=int(config.mamba_d_conv),
            ssd_heads=int(config.n_mamba_heads),
            ssd_head_dim=int(d_inner // config.n_mamba_heads),
            n_groups=int(config.mamba_ngroups),
            gate_norm_groups=int(config.mamba_ngroups),
            gate_norm_eps=1e-5,
            layer_kinds=tuple(config.layers_block_type),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        if config.use_mem_rope:
            return rope_ops.default_inv_freq(int(config.attention_head_dim),
                                             float(config.rope_theta))
        # rope disabled: identity rotation via a zero frequency table
        return np.zeros((int(config.attention_head_dim) // 2,), np.float32)

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: Zamba2ArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        n_hyb = sum(1 for k in a.layer_kinds if k == "hybrid")
        self.kv_cache = {
            "k": jnp.zeros((n_hyb, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((n_hyb, b, a.num_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((a.num_layers, b, a.d_conv, a.conv_dim), dt),
            "ssm": jnp.zeros((a.num_layers, b, a.ssd_heads, a.ssd_head_dim,
                              a.d_state), jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias"}

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        hyb_ids = [i for i, k in enumerate(config.layers_block_type)
                   if k == "hybrid"]
        first = hyb_ids[0]
        st = f"model.layers.{first}.shared_transformer."
        shared = {
            "ln1": get(st + "input_layernorm.weight"),
            "wq": lin_t(st + "self_attn.q_proj.weight"),
            "wk": lin_t(st + "self_attn.k_proj.weight"),
            "wv": lin_t(st + "self_attn.v_proj.weight"),
            "wo": lin_t(st + "self_attn.o_proj.weight"),
            "ln2": get(st + "pre_ff_layernorm.weight"),
            "gate_up": lin_t(st + "feed_forward.gate_up_proj.weight"),
            "down": lin_t(st + "feed_forward.down_proj.weight"),
        }
        # per-invocation LoRA adapters live on the (tied) shared module
        ad = st + "feed_forward.gate_up_proj_adapter_list."
        adapter_a = np.stack([lin_t(f"{ad}{j}.0.weight")
                              for j in range(len(hyb_ids))])
        adapter_b = np.stack([lin_t(f"{ad}{j}.1.weight")
                              for j in range(len(hyb_ids))])
        linear = np.stack([lin_t(f"model.layers.{i}.linear.weight")
                           for i in hyb_ids])

        layers = []
        for i, kind in enumerate(config.layers_block_type):
            p = f"model.layers.{i}."
            mx = (p + "mamba_decoder." if kind == "hybrid" else p)
            lp = {
                "ln1": get(mx + "input_layernorm.weight"),
                "in_proj": lin_t(mx + "mamba.in_proj.weight"),
                "conv_w": np.ascontiguousarray(
                    get(mx + "mamba.conv1d.weight")[:, 0, :].T),
                "conv_b": get(mx + "mamba.conv1d.bias"),
                "dt_bias": get(mx + "mamba.dt_bias"),
                "a_log": get(mx + "mamba.A_log"),
                "d_skip": get(mx + "mamba.D"),
                "gate_norm": get(mx + "mamba.norm.weight"),
                "out_proj": lin_t(mx + "mamba.out_proj.weight"),
            }
            layers.append(lp)
        out = {
            "embed": get("model.embed_tokens.weight"),
            "shared": shared,
            "adapter_a": adapter_a,
            "adapter_b": adapter_b,
            "linear": linear,
            "layers": layers,
            "final_norm": get("model.final_layernorm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
