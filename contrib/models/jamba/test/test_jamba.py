"""jamba parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/jamba/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_jamba_parity():
    """Jamba hybrid: mamba mixers (+dt/B/C norms) + NoPE attention + MoE-every-
    other-layer in one heterogeneous cache pytree."""
    from transformers import JambaConfig, JambaForCausalLM as HFJamba

    from contrib.models.jamba.src.modeling_jamba import JambaForCausalLM

    cfg = JambaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2,
                      attn_layer_period=4, attn_layer_offset=2,
                      expert_layer_period=2, expert_layer_offset=1,
                      num_experts=4, num_experts_per_tok=2,
                      mamba_d_state=8, mamba_d_conv=4, mamba_expand=2,
                      mamba_dt_rank=8, use_mamba_kernels=False,
                      pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFJamba(cfg).eval()
    _run_parity(JambaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
