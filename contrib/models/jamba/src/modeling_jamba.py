"""Jamba (AI21 mamba/attention/MoE hybrid) on the TPU framework (contrib port).

The hub's hybrid-SSM family: mamba mixer layers (with Jamba's dt/B/C RMSNorms)
interleaved with NoPE GQA attention layers (attn_layer_period/offset), every
layer followed by an FFN that is either a dense gated MLP or a sparse MoE
(expert_layer_period/offset, softmax-then-topk gates without renorm). The
hybrid cache pytree carries per-mamba-layer (conv tail, fp32 SSM state) next
to the attention layers' stacked KV. Prefill runs the selective scan as a
`jax.lax.associative_scan` (see contrib/models/mamba); heterogeneous per-layer
params ride a list pytree.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class JambaArchArgs(ModelArchArgs):
    d_inner: int = 0
    d_state: int = 16
    d_conv: int = 4
    dt_rank: int = 0
    layer_kinds: Tuple[str, ...] = ()     # "attention" | "mamba" per layer
    ffn_kinds: Tuple[str, ...] = ()       # "dense" | "moe" per layer
    num_experts: int = 16
    experts_per_tok: int = 2


def _mamba_mixer(lp, hn, args, last_token_idx, conv_state, ssm_state):
    """Jamba mamba mixer (mamba1 + dt/B/C RMSNorms). Prefill when
    last_token_idx is given (associative scan), else one-token decode."""
    w = args.d_conv
    r, s = args.dt_rank, args.d_state
    proj = hn @ lp["in_proj"]
    x, z = proj[..., : args.d_inner], proj[..., args.d_inner :]

    if last_token_idx is not None:                      # prefill
        t = x.shape[1]
        idx = last_token_idx[:, None] + 1 - w + jnp.arange(w)[None, :]
        gathered = jnp.take_along_axis(x, jnp.clip(idx, 0, t - 1)[:, :, None],
                                       axis=1)
        conv_state = jnp.where((idx >= 0)[:, :, None], gathered, 0.0)
        xp = jnp.pad(x, ((0, 0), (w - 1, 0), (0, 0)))
        xc = sum(xp[:, j : j + t, :] * lp["conv_w"][j][None, None, :]
                 for j in range(w)) + lp["conv_b"][None, None, :]
        xc = jax.nn.silu(xc)
    else:                                               # decode (T = 1)
        x0 = x[:, 0]
        conv_state = jnp.concatenate([conv_state[:, 1:], x0[:, None, :]], axis=1)
        xc = jnp.sum(conv_state * lp["conv_w"][None, :, :], axis=1) + lp["conv_b"]
        xc = jax.nn.silu(xc)[:, None, :]

    ssm_p = xc @ lp["x_proj"]
    dt, b_mat, c_mat = ssm_p[..., :r], ssm_p[..., r : r + s], ssm_p[..., r + s :]
    dt = rms_norm(dt, lp["dt_norm"], args.rms_norm_eps)
    b_mat = rms_norm(b_mat, lp["b_norm"], args.rms_norm_eps)
    c_mat = rms_norm(c_mat, lp["c_norm"], args.rms_norm_eps)
    delta = jax.nn.softplus(
        (dt @ lp["dt_proj"] + lp["dt_bias"]).astype(jnp.float32))
    a = -jnp.exp(lp["a_log"].astype(jnp.float32))
    d_a = jnp.exp(delta[..., None] * a[None, None])
    d_bu = (delta[..., None] * b_mat.astype(jnp.float32)[:, :, None, :]
            * xc.astype(jnp.float32)[..., None])

    if last_token_idx is not None:
        t = xc.shape[1]
        valid = (jnp.arange(t)[None, :]
                 <= last_token_idx[:, None])[:, :, None, None]
        d_a = jnp.where(valid, d_a, 1.0)
        d_bu = jnp.where(valid, d_bu, 0.0)

        def comb(l, rr):
            return (rr[0] * l[0], rr[0] * l[1] + rr[1])

        _, h_seq = jax.lax.associative_scan(comb, (d_a, d_bu), axis=1)
        ssm_state = jnp.take_along_axis(
            h_seq, last_token_idx[:, None, None, None], axis=1)[:, 0]
        y = jnp.einsum("btis,bts->bti", h_seq, c_mat.astype(jnp.float32))
    else:
        ssm_state = d_a[:, 0] * ssm_state + d_bu[:, 0]
        y = jnp.einsum("bis,bs->bi", ssm_state,
                       c_mat[:, 0].astype(jnp.float32))[:, None, :]
    y = y + xc.astype(jnp.float32) * lp["d_skip"].astype(jnp.float32)
    y = y.astype(hn.dtype) * jax.nn.silu(z)
    return y @ lp["out_proj"], conv_state.astype(hn.dtype), ssm_state


def _attn(lp, hn, mask, k_cache, v_cache, positions, bucket, args):
    """NoPE GQA attention over one dense cache layer."""
    b, t, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    v = (hn @ lp["wv"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, args.q_size)
    return attn @ lp["wo"], k_cache, v_cache


def _ffn(lp, hn, args, kind):
    if kind == "dense":
        return (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
    # sparse MoE: softmax over ALL experts, top-k gates WITHOUT renorm
    b, t, hdim = hn.shape
    x = hn.reshape(b * t, hdim)
    logits = (x.astype(jnp.float32) @ lp["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, args.experts_per_tok)
    gates = jnp.einsum("nk,nke->ne", top_vals,
                       jax.nn.one_hot(top_idx, args.num_experts,
                                      dtype=jnp.float32))
    inter = (jax.nn.silu(jnp.einsum("nh,ehi->eni", x, lp["moe_wg"]))
             * jnp.einsum("nh,ehi->eni", x, lp["moe_wu"]))
    per_expert = jnp.einsum("eni,eih->enh", inter, lp["moe_wd"])
    out = jnp.einsum("enh,ne->nh", per_expert, gates.astype(per_expert.dtype))
    return out.reshape(b, t, hdim).astype(hn.dtype)


def _forward(params, args: JambaArchArgs, h, mask, cache, positions, bucket,
             last_token_idx):
    ks, vs, convs, ssms = [], [], [], []
    ai = mi = 0
    for li, kind in enumerate(args.layer_kinds):
        lp = params["layers"][li]
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        if kind == "attention":
            out, kc, vc = _attn(lp, hn, mask, cache["k"][ai], cache["v"][ai],
                                positions, bucket, args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        else:
            out, conv_state, ssm_state = _mamba_mixer(
                lp, hn, args, last_token_idx,
                cache["conv"][mi] if positions is not None else None,
                cache["ssm"][mi] if positions is not None else None)
            convs.append(conv_state)
            ssms.append(ssm_state)
            mi += 1
        h = h + out
        hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
        h = h + _ffn(lp, hn, args, args.ffn_kinds[li])
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs),
                 "conv": jnp.stack(convs), "ssm": jnp.stack(ssms)}
    return h, out_cache


def prefill_forward(params, args: JambaArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    t = input_ids.shape[1]
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = (h_last @ params["lm_head"]).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: JambaArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Jamba decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= position_ids[:, None, None, None]
    h, out_cache = _forward(params, args, h, mask, cache, position_ids,
                            decode_bucket, None)
    logits = (h @ params["lm_head"]).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class JambaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "attn_layer_period", "attn_layer_offset",
                           "expert_layer_period", "expert_layer_offset",
                           "num_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        for attr, default in (("rms_norm_eps", 1e-6), ("mamba_d_state", 16),
                              ("mamba_d_conv", 4), ("mamba_expand", 2),
                              ("mamba_dt_rank", "auto"),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if self.mamba_dt_rank == "auto":
            import math

            self.mamba_dt_rank = math.ceil(self.hidden_size / 16)

    def layer_kinds(self):
        return tuple(
            "attention" if i % self.attn_layer_period == self.attn_layer_offset
            else "mamba" for i in range(self.num_hidden_layers))

    def ffn_kinds(self):
        return tuple(
            "moe" if (self.num_experts > 1
                      and i % self.expert_layer_period == self.expert_layer_offset)
            else "dense" for i in range(self.num_hidden_layers))


class JambaForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "Jamba (hybrid SSM)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return JambaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> JambaArchArgs:
        return JambaArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            d_inner=int(config.mamba_expand * config.hidden_size),
            d_state=int(config.mamba_d_state),
            d_conv=int(config.mamba_d_conv),
            dt_rank=int(config.mamba_dt_rank),
            layer_kinds=config.layer_kinds(),
            ffn_kinds=config.ffn_kinds(),
            num_experts=int(config.num_experts),
            experts_per_tok=int(config.num_experts_per_tok),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return np.zeros((1,), np.float32)        # Jamba attention is NoPE

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: JambaArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        n_att = sum(1 for k in a.layer_kinds if k == "attention")
        n_mamba = len(a.layer_kinds) - n_att
        self.kv_cache = {
            "k": jnp.zeros((max(n_att, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((max(n_att, 1), b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((max(n_mamba, 1), b, a.d_conv, a.d_inner), dt),
            "ssm": jnp.zeros((max(n_mamba, 1), b, a.d_inner, a.d_state),
                             jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias"}

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        kinds = config.layer_kinds()
        ffns = config.ffn_kinds()
        layers = []
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            lp: Dict[str, np.ndarray] = {
                "ln1": get(p + "input_layernorm.weight"),
                "ln2": get(p + "pre_ff_layernorm.weight"),
            }
            if kinds[i] == "attention":
                lp["wq"] = lin_t(p + "self_attn.q_proj.weight")
                lp["wk"] = lin_t(p + "self_attn.k_proj.weight")
                lp["wv"] = lin_t(p + "self_attn.v_proj.weight")
                lp["wo"] = lin_t(p + "self_attn.o_proj.weight")
            else:
                mx = p + "mamba."
                lp["in_proj"] = lin_t(mx + "in_proj.weight")
                lp["conv_w"] = np.ascontiguousarray(
                    get(mx + "conv1d.weight")[:, 0, :].T)
                lp["conv_b"] = get(mx + "conv1d.bias")
                lp["x_proj"] = lin_t(mx + "x_proj.weight")
                lp["dt_proj"] = lin_t(mx + "dt_proj.weight")
                lp["dt_bias"] = get(mx + "dt_proj.bias")
                lp["dt_norm"] = get(mx + "dt_layernorm.weight")
                lp["b_norm"] = get(mx + "b_layernorm.weight")
                lp["c_norm"] = get(mx + "c_layernorm.weight")
                lp["a_log"] = get(mx + "A_log")
                lp["d_skip"] = get(mx + "D")
                lp["out_proj"] = lin_t(mx + "out_proj.weight")
            if ffns[i] == "moe":
                m = p + "feed_forward."
                lp["router"] = lin_t(m + "router.weight")
                E = config.num_experts
                lp["moe_wg"] = np.stack(
                    [lin_t(m + f"experts.{e}.gate_proj.weight")
                     for e in range(E)])
                lp["moe_wu"] = np.stack(
                    [lin_t(m + f"experts.{e}.up_proj.weight")
                     for e in range(E)])
                lp["moe_wd"] = np.stack(
                    [lin_t(m + f"experts.{e}.down_proj.weight")
                     for e in range(E)])
            else:
                m = p + "feed_forward."
                lp["wg"] = lin_t(m + "gate_proj.weight")
                lp["wu"] = lin_t(m + "up_proj.weight")
                lp["wd"] = lin_t(m + "down_proj.weight")
            layers.append(lp)
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": layers,
            "final_norm": get("model.final_layernorm.weight"),
            "lm_head": lin_t("lm_head.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
