"""Bamba (IBM mamba2/attention sequential hybrid) on the TPU framework
(contrib port).

≈ reference contrib hybrid family. Jamba's heterogeneous-layer layout with
mamba2 SSD mixers: each layer is ln1 → (SSD mixer OR partial-rotary GQA
attention) → residual, then pre-ff norm → dense gated MLP → residual (HF
`BambaDecoderLayer`). The mixer math (grouped B/C expand, joint x|B|C conv,
gate-then-norm gated RMSNorm, associative-scan prefill) is imported from
contrib/models/mamba2; the hybrid cache stacks attention KV separately from
the mamba conv-tail/fp32-SSM states.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from contrib.models.mamba2.src.modeling_mamba2 import (Mamba2ArchArgs,
                                                       _mixer_decode,
                                                       _mixer_prefill)
from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import (
    ModelArchArgs, causal_mask)
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.attention import attend
from neuronx_distributed_inference_tpu.ops.norms import rms_norm
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


@dataclass(frozen=True)
class BambaArchArgs(Mamba2ArchArgs):
    layer_kinds: Tuple[str, ...] = ()
    rotary_dim: int = 0
    attention_scale: Optional[float] = None   # None = head_dim**-0.5


def _rot_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _partial_rotary(q, k, cos, sin, rd):
    """Rotate the first ``rd`` dims of q/k, pass the rest through (HF partial
    rotary convention used by `BambaAttention`)."""
    cos, sin = cos[:, None, :, :], sin[:, None, :, :]
    qr, qp = q[..., :rd].astype(jnp.float32), q[..., rd:]
    kr, kp = k[..., :rd].astype(jnp.float32), k[..., rd:]
    qr = qr * cos + _rot_half(qr) * sin
    kr = kr * cos + _rot_half(kr) * sin
    q = jnp.concatenate([qr.astype(q.dtype), qp], axis=-1)
    k = jnp.concatenate([kr.astype(k.dtype), kp], axis=-1)
    return q, k


def _attn(lp, hn, cos, sin, mask, k_cache, v_cache, positions, bucket, args):
    b, t, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, t, args.num_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    k = (hn @ lp["wk"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    v = (hn @ lp["wv"]).reshape(b, t, args.num_kv_heads, args.head_dim
                                ).transpose(0, 2, 1, 3)
    q, k = _partial_rotary(q, k, cos, sin, args.rotary_dim)
    if positions is None:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        k_att, v_att = k, v
    else:
        def _one(row_c, row_n, p):
            return jax.lax.dynamic_update_slice(
                row_c, row_n.astype(row_c.dtype), (0, p, 0))

        k_cache = jax.vmap(_one)(k_cache, k, positions)
        v_cache = jax.vmap(_one)(v_cache, v, positions)
        k_att = jax.lax.slice_in_dim(k_cache, 0, bucket, axis=2).astype(q.dtype)
        v_att = jax.lax.slice_in_dim(v_cache, 0, bucket, axis=2).astype(q.dtype)
    attn = attend(q, k_att, v_att, mask=mask, scale=args.attention_scale)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, args.q_size)
    return attn @ lp["wo"], k_cache, v_cache


def _forward(params, args: BambaArchArgs, h, cos, sin, mask, cache, positions,
             bucket, last_token_idx):
    ks, vs, convs, ssms = [], [], [], []
    ai = mi = 0
    for li, kind in enumerate(args.layer_kinds):
        lp = params["layers"][li]
        hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
        if kind == "attention":
            out, kc, vc = _attn(lp, hn, cos, sin, mask, cache["k"][ai],
                                cache["v"][ai], positions, bucket, args)
            ks.append(kc)
            vs.append(vc)
            ai += 1
        elif positions is None:
            out, conv_state, ssm_state = _mixer_prefill(lp, hn, last_token_idx,
                                                        args)
            convs.append(conv_state)
            ssms.append(ssm_state)
            mi += 1
        else:
            out, conv_state, ssm_state = _mixer_decode(
                lp, hn, cache["conv"][mi], cache["ssm"][mi], args)
            convs.append(conv_state)
            ssms.append(ssm_state)
            mi += 1
        h = h + out
        hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
        h = h + (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
    h = rms_norm(h, params["final_norm"], args.rms_norm_eps)
    out_cache = {"k": jnp.stack(ks) if ks else cache["k"],
                 "v": jnp.stack(vs) if vs else cache["v"],
                 "conv": jnp.stack(convs) if convs else cache["conv"],
                 "ssm": jnp.stack(ssms) if ssms else cache["ssm"]}
    return h, out_cache


def prefill_forward(params, args: BambaArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    adapter_ids=None, use_ring=False, return_hidden=False):
    h = jnp.take(params["embed"], input_ids, axis=0)
    t = input_ids.shape[1]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids)
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask &= causal_mask(t, t)[None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache, None, None,
                            last_token_idx)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h_last @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


def decode_forward(params, args: BambaArchArgs, input_ids, position_ids, cache,
                   decode_bucket, mesh=None, rules=None, adapter_ids=None,
                   tree=None, return_hidden=False, **_ignored):
    if input_ids.shape[1] != 1 or tree is not None:
        raise ValueError("Bamba decode is single-token only")
    h = jnp.take(params["embed"], input_ids, axis=0)
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"],
                                        position_ids[:, None])
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= position_ids[:, None, None, None]
    h, out_cache = _forward(params, args, h, cos, sin, mask, cache,
                            position_ids, decode_bucket, None)
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    logits = (h @ head).astype(jnp.float32)
    if return_hidden:
        return logits, out_cache, h
    return logits, out_cache


class BambaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "mamba_n_heads", "mamba_d_state")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("mamba_d_conv", 4), ("mamba_expand", 2),
                              ("mamba_n_groups", 1),
                              ("partial_rotary_factor", 0.5),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if not getattr(self, "layers_block_type", None):
            # BambaConfig derives layers_block_type from attn_layer_indices
            # and to_dict() drops the derived list; rebuild it the same way
            # (or take `layer_types` if a config serialized it under that key)
            lt = getattr(self, "layer_types", None)
            if lt:
                self.layers_block_type = list(lt)
            else:
                idx = set(getattr(self, "attn_layer_indices", None) or [])
                self.layers_block_type = [
                    "attention" if i in idx else "mamba"
                    for i in range(self.num_hidden_layers)]
        for flag in ("attention_bias", "mamba_proj_bias"):
            if getattr(self, flag, False):
                raise ValueError(f"Bamba {flag}=True is not ported (released "
                                 "checkpoints ship bias-free projections)")


class BambaForCausalLM(TpuModelForCausalLM):
    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config,
                                  "Bamba (mamba2/attention hybrid)")
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return BambaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> BambaArchArgs:
        d_inner = int(config.mamba_expand * config.hidden_size)
        return BambaArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            tie_word_embeddings=bool(config.tie_word_embeddings),
            d_inner=d_inner,
            d_state=int(config.mamba_d_state),
            d_conv=int(config.mamba_d_conv),
            ssd_heads=int(config.mamba_n_heads),
            ssd_head_dim=int(d_inner // config.mamba_n_heads),
            n_groups=int(config.mamba_n_groups),
            layer_kinds=tuple(config.layers_block_type),
            rotary_dim=int(config.head_dim * float(config.partial_rotary_factor)),
        )

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        rd = int(config.head_dim * float(config.partial_rotary_factor))
        return rope_ops.default_inv_freq(rd, float(config.rope_theta))

    def reset_cache(self, batch_size: Optional[int] = None) -> None:
        a: BambaArchArgs = self.arch_args
        b = batch_size or self.tpu_config.max_batch_size
        dt = self.tpu_config.jax_dtype
        n_attn = sum(1 for k in a.layer_kinds if k == "attention")
        n_mamba = a.num_layers - n_attn
        self.kv_cache = {
            "k": jnp.zeros((n_attn, b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "v": jnp.zeros((n_attn, b, a.num_kv_heads,
                            self.tpu_config.seq_len, a.head_dim), dt),
            "conv": jnp.zeros((n_mamba, b, a.d_conv, a.conv_dim), dt),
            "ssm": jnp.zeros((n_mamba, b, a.ssd_heads, a.ssd_head_dim,
                              a.d_state), jnp.float32),
        }

    def _put_params(self, host_params) -> None:
        dtype = self.tpu_config.jax_dtype
        fp32_keys = {"a_log", "d_skip", "dt_bias"}

        def _put(path, x):
            arr = np.asarray(x)
            last = getattr(path[-1], "key", None) if path else None
            if arr.dtype.kind == "f":
                arr = arr.astype(np.float32 if last in fp32_keys else dtype)
            return jax.device_put(arr)

        self.params = jax.tree_util.tree_map_with_path(_put, host_params)
        self.reset_cache()

    def init_random_params(self, key):
        raise NotImplementedError("load from an HF checkpoint or state dict")

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = []
        for i, kind in enumerate(config.layers_block_type):
            p = f"model.layers.{i}."
            lp = {
                "ln1": get(p + "input_layernorm.weight"),
                "ln2": get(p + "pre_ff_layernorm.weight"),
                "wg": lin_t(p + "feed_forward.gate_proj.weight"),
                "wu": lin_t(p + "feed_forward.up_proj.weight"),
                "wd": lin_t(p + "feed_forward.down_proj.weight"),
            }
            if kind == "attention":
                lp.update({
                    "wq": lin_t(p + "self_attn.q_proj.weight"),
                    "wk": lin_t(p + "self_attn.k_proj.weight"),
                    "wv": lin_t(p + "self_attn.v_proj.weight"),
                    "wo": lin_t(p + "self_attn.o_proj.weight"),
                })
            else:
                mx = p + "mamba."
                lp.update({
                    "in_proj": lin_t(mx + "in_proj.weight"),
                    "conv_w": np.ascontiguousarray(
                        get(mx + "conv1d.weight")[:, 0, :].T),
                    "conv_b": get(mx + "conv1d.bias"),
                    "dt_bias": get(mx + "dt_bias"),
                    "a_log": get(mx + "A_log"),
                    "d_skip": get(mx + "D"),
                    "gate_norm": get(mx + "norm.weight"),
                    "out_proj": lin_t(mx + "out_proj.weight"),
                })
            layers.append(lp)
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": layers,
            "final_norm": get("model.final_layernorm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
