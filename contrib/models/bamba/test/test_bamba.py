"""bamba parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/bamba/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_bamba_parity():
    """Bamba: sequential mamba2/attention hybrid — SSD mixer layers and
    partial-rotary GQA attention layers alternate per layers_block_type,
    each followed by a dense gated MLP."""
    from transformers import BambaConfig, BambaForCausalLM as HFBamba

    from contrib.models.bamba.src.modeling_bamba import BambaForCausalLM

    cfg = BambaConfig(vocab_size=256, hidden_size=32, num_hidden_layers=3,
                      num_attention_heads=4, num_key_value_heads=2,
                      intermediate_size=64, mamba_n_heads=8, mamba_d_head=8,
                      mamba_n_groups=2, mamba_d_state=8, mamba_d_conv=4,
                      mamba_expand=2, attn_layer_indices=[1],
                      partial_rotary_factor=0.5, rope_theta=10000.0,
                      tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(0)
    hf = HFBamba(cfg).eval()
    _run_parity(BambaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
