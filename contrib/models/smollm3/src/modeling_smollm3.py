"""SmolLM3 (HuggingFace) on the TPU framework (contrib port).

Llama geometry where every ``no_rope_layer_interval``-th layer uses NO
positional encoding (NoPE). Mapping: the shared layer-pattern machinery with
rope layers as the "sliding" kind whose window equals the full sequence
(rolling cache width == seq_len, i.e. plain causal attention) on the real rope
table, and NoPE layers as the "full" kind on a ZERO inv-freq table (identity
rotation) — no new primitives.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class SmolLM3InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size", "no_rope_layers")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 2000000.0), ("rms_norm_eps", 1e-6),
                              ("attention_bias", False),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if getattr(self, "use_sliding_window", False):
            raise ValueError("SmolLM3 sliding-window variants are not ported yet")


class SmolLM3ForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return SmolLM3InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        # no_rope_layers[i] == 1 -> rope ON ("sliding" kind, full-width window);
        # 0 -> NoPE ("full" kind on the zeroed global table)
        pattern = tuple("sliding" if on else "full"
                        for on in config.no_rope_layers)
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_bias=bool(config.attention_bias),
            sliding_window=int(config.tpu_config.seq_len),
            layer_pattern=pattern,
            local_rope_theta=float(config.rope_theta),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # NoPE layers ride the zeroed global table (identity rotation)
        return np.zeros((config.head_dim // 2,), np.float32)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
            "rope_inv_freq_local": rope_ops.default_inv_freq(
                config.head_dim, float(config.rope_theta)),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
