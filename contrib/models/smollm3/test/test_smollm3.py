"""smollm3 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/smollm3/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_smollm3_parity():
    """SmolLM3: NoPE every 4th layer via the pattern machinery — rope layers as
    full-width-window 'sliding' kind, NoPE layers on a zeroed rope table."""
    from transformers import SmolLM3Config, SmolLM3ForCausalLM as HFSmolLM3

    from contrib.models.smollm3.src.modeling_smollm3 import SmolLM3ForCausalLM

    cfg = SmolLM3Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=4, num_attention_heads=4,
                        num_key_value_heads=2,
                        no_rope_layers=[1, 1, 1, 0], use_sliding_window=False,
                        pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFSmolLM3(cfg).eval()
    _run_parity(SmolLM3ForCausalLM, hf, cfg)
