"""stablelm parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/stablelm/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_stablelm_parity():
    from transformers import StableLmConfig, StableLmForCausalLM as HFStableLm

    from contrib.models.stablelm.src.modeling_stablelm import StableLmForCausalLM

    cfg = StableLmConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         intermediate_size=128, partial_rotary_factor=0.25,
                         use_qkv_bias=True, max_position_embeddings=128,
                         attention_dropout=0.0)
    torch.manual_seed(0)
    hf = HFStableLm(cfg).eval()
    _run_parity(StableLmForCausalLM, hf, cfg)
