"""StableLM-2 on the TPU framework (contrib port).

Exercises: partial rotary + biased LayerNorm + GQA + optional qkv biases over the
gated-MLP core.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class StableLmInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0),
                              ("partial_rotary_factor", 0.25),
                              ("layer_norm_eps", 1e-5), ("hidden_act", "silu"),
                              ("use_qkv_bias", False),
                              ("use_parallel_residual", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if self.use_parallel_residual:
            raise NotImplementedError("parallel-residual stablelm variants are "
                                      "not covered by this port")


class StableLmForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return StableLmInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        d = h // config.num_attention_heads
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=d,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            activation=config.hidden_act,
            norm_type="layer", norm_bias=True,
            attention_bias=bool(config.use_qkv_bias),
            rotary_dim=int(d * config.partial_rotary_factor),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return rope_ops.default_inv_freq(int(d * config.partial_rotary_factor),
                                         float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        args = cls.arch_args_from_config(config)

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ["ln1", "ln1_b", "wq", "wk", "wv", "wo", "ln2", "ln2_b",
                "wg", "wu", "wd"]
        if args.attention_bias:
            keys += ["bq", "bk", "bv"]
        layers = {k: [] for k in keys}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            if args.attention_bias:
                layers["bq"].append(get(p + "self_attn.q_proj.bias"))
                layers["bk"].append(get(p + "self_attn.k_proj.bias"))
                layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "final_norm_b": get("model.norm.bias"),
            "lm_head": lin_t("lm_head.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
