"""GPT-J (EleutherAI 6B) on the TPU framework (contrib port).

Single-LayerNorm parallel-residual block (h = x + attn(ln(x)) + mlp(ln(x))),
interleaved partial rotary (rotary_dim=64 of head_dim 256), plain biased
gelu MLP, biased lm_head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class GPTJInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("n_embd", "n_layer", "n_head", "vocab_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rotary_dim", 64), ("layer_norm_epsilon", 1e-5),
                              ("n_inner", None),
                              ("activation_function", "gelu_new"),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                if default is not None or not hasattr(self, attr):
                    setattr(self, attr, default)
        if self.n_inner is None:
            self.n_inner = 4 * self.n_embd


class GPTJForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GPTJInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        d = config.n_embd // config.n_head
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.n_embd,
            num_layers=config.n_layer,
            num_heads=config.n_head,
            num_kv_heads=config.n_head,
            head_dim=d,
            intermediate_size=config.n_inner,
            rms_norm_eps=config.layer_norm_epsilon,
            norm_type="layer",
            norm_bias=True,
            activation=config.activation_function,
            mlp_kind="plain",
            mlp_bias=True,
            o_bias=False,
            parallel_residual=True,
            shared_ln=True,
            rotary_dim=int(config.rotary_dim),
            rope_interleaved=True,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(int(config.rotary_dim), 10000.0)

    def logical_axes(self) -> Dict:
        from neuronx_distributed_inference_tpu.models import base as model_base

        axes = model_base.param_logical_axes(self.arch_args)
        axes["lm_head_b"] = ("vocab",)
        return axes

    def init_random_params(self, key) -> Dict:
        import jax.numpy as jnp

        params = super().init_random_params(key)
        params["lm_head_b"] = jnp.zeros((self.arch_args.vocab_size,),
                                        self.tpu_config.jax_dtype)
        return params

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "wo",
                                  "ln2", "ln2_b", "wg", "bg", "wd", "bd")}
        for i in range(config.n_layer):
            p = f"transformer.h.{i}."
            layers["wq"].append(lin_t(p + "attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "attn.out_proj.weight"))
            ln = get(p + "ln_1.weight")
            layers["ln1"].append(ln)
            layers["ln1_b"].append(get(p + "ln_1.bias"))
            layers["ln2"].append(np.ones_like(ln))       # unused under shared_ln
            layers["ln2_b"].append(np.zeros_like(ln))
            layers["wg"].append(lin_t(p + "mlp.fc_in.weight"))
            layers["bg"].append(get(p + "mlp.fc_in.bias"))
            layers["wd"].append(lin_t(p + "mlp.fc_out.weight"))
            layers["bd"].append(get(p + "mlp.fc_out.bias"))
        return {
            "embed": get("transformer.wte.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "lm_head": lin_t("lm_head.weight"),
            "lm_head_b": get("lm_head.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
