"""gptj parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gptj/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_gptj_parity():
    from transformers import GPTJConfig, GPTJForCausalLM as HFGPTJ

    from contrib.models.gptj.src.modeling_gptj import GPTJForCausalLM

    cfg = GPTJConfig(vocab_size=256, n_embd=64, n_layer=2, n_head=4,
                     rotary_dim=8, n_inner=128, resid_pdrop=0.0,
                     embd_pdrop=0.0, attn_pdrop=0.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFGPTJ(cfg).eval()
    _run_parity(GPTJForCausalLM, hf, cfg)
