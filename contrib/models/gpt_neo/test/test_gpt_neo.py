"""gpt_neo parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/gpt_neo/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_gpt_neo_parity():
    """GPT-Neo: alternating global/local(window) attention with learned
    positions and UNSCALED scores over the layer-pattern machinery."""
    from transformers import GPTNeoConfig, GPTNeoForCausalLM as HFNeo

    from contrib.models.gpt_neo.src.modeling_gpt_neo import GPTNeoForCausalLM

    cfg = GPTNeoConfig(vocab_size=256, hidden_size=64, num_layers=4,
                       num_heads=4, window_size=16, intermediate_size=128,
                       attention_types=[[["global", "local"], 2]],
                       resid_dropout=0.0, embed_dropout=0.0,
                       attention_dropout=0.0, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFNeo(cfg).eval()
    _run_parity(GPTNeoForCausalLM, hf, cfg)
