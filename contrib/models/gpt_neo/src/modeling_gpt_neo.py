"""GPT-Neo (EleutherAI) on the TPU framework (contrib port).

Alternating global/local(256-window) attention layers over learned positions
and UNSCALED attention scores (scale = 1.0) — the local layers ride the shared
layer-pattern machinery's rolling window caches, positions come from the
learned table (no rope: both rope tables zeroed), plain biased gelu MLP.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class GPTNeoInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_layers", "num_heads",
                           "vocab_size", "attention_types")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5),
                              ("window_size", 256),
                              ("intermediate_size", None),
                              ("activation_function", "gelu_new"),
                              ("max_position_embeddings", 2048),
                              ("tie_word_embeddings", True)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                if default is not None or not hasattr(self, attr):
                    setattr(self, attr, default)
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    def layer_pattern(self):
        kinds = []
        for block, times in self.attention_types:
            kinds.extend(list(block) * times)
        return tuple("sliding" if k == "local" else "full"
                     for k in kinds[: self.num_layers])


class GPTNeoForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GPTNeoInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        d = config.hidden_size // config.num_heads
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_layers,
            num_heads=config.num_heads,
            num_kv_heads=config.num_heads,
            head_dim=d,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_epsilon,
            norm_type="layer",
            norm_bias=True,
            activation=config.activation_function,
            mlp_kind="plain",
            mlp_bias=True,
            o_bias=True,
            attention_scale=1.0,                 # GPT-Neo does not scale scores
            learned_pos=True,
            sliding_window=int(config.window_size),
            layer_pattern=config.layer_pattern(),
            local_rope_theta=10000.0,   # registers the local table key; both
            #                             tables are zeroed (learned positions)
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_heads
        return np.zeros((d // 2,), np.float32)   # no rope: learned positions

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "wo", "bo",
                                  "ln2", "ln2_b", "wg", "bg", "wd", "bd")}
        for i in range(config.num_layers):
            p = f"transformer.h.{i}."
            layers["wq"].append(lin_t(p + "attn.attention.q_proj.weight"))
            layers["wk"].append(lin_t(p + "attn.attention.k_proj.weight"))
            layers["wv"].append(lin_t(p + "attn.attention.v_proj.weight"))
            layers["wo"].append(lin_t(p + "attn.attention.out_proj.weight"))
            layers["bo"].append(get(p + "attn.attention.out_proj.bias"))
            layers["ln1"].append(get(p + "ln_1.weight"))
            layers["ln1_b"].append(get(p + "ln_1.bias"))
            layers["ln2"].append(get(p + "ln_2.weight"))
            layers["ln2_b"].append(get(p + "ln_2.bias"))
            layers["wg"].append(lin_t(p + "mlp.c_fc.weight"))
            layers["bg"].append(get(p + "mlp.c_fc.bias"))
            layers["wd"].append(lin_t(p + "mlp.c_proj.weight"))
            layers["bd"].append(get(p + "mlp.c_proj.bias"))
        return {
            "embed": get("transformer.wte.weight"),
            "pos_embed": get("transformer.wpe.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
            "rope_inv_freq_local": cls.inv_freq_from_config(config),
        }
