"""minicpm parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/minicpm/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""

import math  # noqa: F401

import numpy as np
import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_minicpm4_parity():
    """MiniCPM4: muP scaling family (scale_emb=2, scale_depth/sqrt(L) branch
    multiplier, hidden/(H/dim_model_base) logit divisor) + LongRoPE ext
    factors with the sqrt(1+ln s/ln orig) cos/sin magnitude."""
    from contrib.models.minicpm.src.modeling_minicpm import (
        MiniCPMForCausalLM, _longrope_params)

    rs = {"rope_type": "longrope",
          "short_factor": [1.0] * 8, "long_factor": list(np.linspace(1, 3, 8)),
          "original_max_position_embeddings": 32}
    cfg = dict(model_type="minicpm", vocab_size=256, hidden_size=64,
               intermediate_size=128, num_hidden_layers=2,
               num_attention_heads=4, num_key_value_heads=2,
               rms_norm_eps=1e-5, rope_theta=10000.0, scale_emb=2.0,
               scale_depth=1.4, dim_model_base=32,
               max_position_embeddings=128, rope_scaling=rs,
               tie_word_embeddings=False)

    class _C:  # mimic config attrs for the helper
        pass
    c = _C()
    c.rope_scaling, c.max_position_embeddings = rs, 128
    factors, attn_scale = _longrope_params(c)
    assert attn_scale > 1.0                  # long branch engaged

    base = (10000.0 ** (-np.arange(0, 16, 2) / 16)).astype(np.float32)
    torch.manual_seed(0)
    oracle = _OracleModel(256, 64, 128, 2, 4, 2, 16, eps=1e-5,
                          inv_freq=base / factors, attn_scale=attn_scale,
                          scale_emb=2.0, res_mult=1.4 / math.sqrt(2),
                          logits_div=64 / 32).eval()
    _run_parity_oracle(MiniCPMForCausalLM, oracle, cfg)
