"""MiniCPM4 (OpenBMB) on the TPU framework (contrib port).

≈ reference `contrib/models/MiniCPM4-8B/src/modeling_minicpm.py`. Llama
geometry with the muP scaling family: embeddings × scale_emb, every residual
branch × scale_depth/sqrt(num_layers), and the final hidden divided by
(hidden_size / dim_model_base) before the lm_head — mapped onto
embedding_multiplier / residual_multiplier / logits_scale. LongRoPE
(rope_type "longrope") divides inv_freq by the per-dim short/long ext factors
(long when max_position_embeddings exceeds the original window) and scales
cos/sin by sqrt(1 + ln(s)/ln(orig_max)).
"""

import math
from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


def _longrope_params(config):
    rs = getattr(config, "rope_scaling", None) or {}
    rtype = rs.get("rope_type", rs.get("type", "default"))
    if rtype != "longrope":
        if rtype != "default":
            raise NotImplementedError(
                f"minicpm port supports rope_type 'longrope'/'default', got "
                f"{rtype!r}")
        return None
    orig = rs.get("original_max_position_embeddings",
                  config.max_position_embeddings)
    # static graphs must pick ONE factor set: choose by the context the engine
    # actually serves (tpu seq_len when known, else the config window) — the
    # long branch only engages when serving beyond the original window, so
    # typical-length prompts keep HF's short_factor table
    tc = getattr(config, "tpu_config", None)
    served = (tc.seq_len if tc is not None
              else config.max_position_embeddings)
    use_long = served > orig
    factors = np.asarray(rs.get("long_factor" if use_long else "short_factor"),
                         np.float32)
    # the cos/sin magnitude factor is a CONSTANT from the config window
    # (HF longrope convention), independent of which factor table serves
    scale = config.max_position_embeddings / orig
    attn = (math.sqrt(1 + math.log(scale) / math.log(orig))
            if scale > 1.0 else 1.0)
    return factors, attn


class MiniCPMInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-6),
                              ("scale_emb", 1.0), ("dim_model_base", None),
                              ("scale_depth", 1.0), ("rope_scaling", None),
                              ("max_position_embeddings", 4096),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if self.dim_model_base is None:
            self.dim_model_base = self.hidden_size
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class MiniCPMForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return MiniCPMInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        lr = _longrope_params(config)
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            embedding_multiplier=float(config.scale_emb),
            residual_multiplier=float(config.scale_depth)
            / math.sqrt(config.num_hidden_layers),
            logits_scale=float(config.dim_model_base) / config.hidden_size,
            rope_attention_scaling=(lr[1] if lr is not None else 1.0),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        base = rope_ops.default_inv_freq(config.head_dim,
                                         float(config.rope_theta))
        lr = _longrope_params(config)
        if lr is not None:
            base = base / lr[0]
        return base

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo",
                                  "ln2", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["wg"].append(lin_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(lin_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
