"""olmoe parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/olmoe/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_olmoe_parity():
    from transformers import OlmoeConfig, OlmoeForCausalLM as HFOlmoe

    from contrib.models.olmoe.src.modeling_olmoe import OlmoeForCausalLM

    cfg = OlmoeConfig(vocab_size=256, hidden_size=64, intermediate_size=48,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, num_experts=4,
                      num_experts_per_tok=2, norm_topk_prob=False,
                      pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFOlmoe(cfg).eval()
    _run_parity(OlmoeForCausalLM, hf, cfg, atol=1e-3, rtol=1e-3)
