"""OLMoE (AI2 mixture-of-experts) on the TPU framework (contrib port).

Fine-grained MoE (64 experts, top-8, gates from the full softmax WITHOUT
renormalization) with full-width q/k RMSNorm.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class OlmoeInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size",
                           "num_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("norm_topk_prob", False),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class OlmoeForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return OlmoeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            qk_norm=True,
            qk_norm_scope="full",
            moe=MoEArgs(num_experts=config.num_experts,
                        experts_per_tok=config.num_experts_per_tok,
                        norm_topk_prob=bool(config.norm_topk_prob)),
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        E = config.num_experts
        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo",
                                  "q_norm", "k_norm",
                                  "ln2", "router", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
            layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            m = p + "mlp."
            layers["router"].append(lin_t(m + "gate.weight"))
            layers["wg"].append(np.stack(
                [lin_t(m + f"experts.{e}.gate_proj.weight") for e in range(E)]))
            layers["wu"].append(np.stack(
                [lin_t(m + f"experts.{e}.up_proj.weight") for e in range(E)]))
            layers["wd"].append(np.stack(
                [lin_t(m + f"experts.{e}.down_proj.weight") for e in range(E)]))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
