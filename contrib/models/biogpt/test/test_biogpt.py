"""biogpt parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/biogpt/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_biogpt_parity():
    from transformers import BioGptConfig, BioGptForCausalLM as HFBioGpt

    from contrib.models.biogpt.src.modeling_biogpt import BioGptForCausalLM

    cfg = BioGptConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=128,
                       max_position_embeddings=128, scale_embedding=True,
                       hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                       activation_dropout=0.0)
    torch.manual_seed(0)
    hf = HFBioGpt(cfg).eval()
    # sqrt(hidden) embedding scaling amplifies the (benign) score-scaling-order
    # difference; greedy tokens still match exactly
    _run_parity(BioGptForCausalLM, hf, cfg, atol=5e-3, rtol=5e-3)
