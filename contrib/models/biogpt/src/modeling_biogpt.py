"""BioGPT on the TPU framework (contrib port, ≈ reference `contrib/models/biogpt/`).

OPT-shaped pre-norm decoder with sqrt(hidden) embedding scaling, learned positions
at OPT's +2 offset, biased LayerNorm + gelu plain MLP, tied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class BioGptInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("hidden_act", "gelu"), ("scale_embedding", True),
                              ("layer_norm_eps", 1e-12)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class BioGptForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return BioGptInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_attention_heads,
            head_dim=h // config.num_attention_heads,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            activation=config.hidden_act,
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            learned_pos=True, pos_offset=2,
            embedding_multiplier=(float(h) ** 0.5 if config.scale_embedding
                                  else 1.0),
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return np.zeros((d // 2,), np.float32)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        for i in range(config.num_hidden_layers):
            p = f"biogpt.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.out_proj.weight"))
            layers["bo"].append(get(p + "self_attn.out_proj.bias"))
            layers["ln1"].append(get(p + "self_attn_layer_norm.weight"))
            layers["ln1_b"].append(get(p + "self_attn_layer_norm.bias"))
            layers["ln2"].append(get(p + "final_layer_norm.weight"))
            layers["ln2_b"].append(get(p + "final_layer_norm.bias"))
            layers["wg"].append(lin_t(p + "fc1.weight"))
            layers["bg"].append(get(p + "fc1.bias"))
            layers["wd"].append(lin_t(p + "fc2.weight"))
            layers["bd"].append(get(p + "fc2.bias"))
        return {
            "embed": get("biogpt.embed_tokens.weight"),
            "pos_embed": get("biogpt.embed_positions.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("biogpt.layer_norm.weight"),
            "final_norm_b": get("biogpt.layer_norm.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
