"""glm parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/glm/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_glm_parity():
    from transformers import GlmConfig, GlmForCausalLM as HFGlm

    from contrib.models.glm.src.modeling_glm import GlmForCausalLM

    cfg = GlmConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, head_dim=16,
                    partial_rotary_factor=0.5, attention_bias=True,
                    pad_token_id=0, eos_token_id=2,
                    tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFGlm(cfg).eval()
    _run_parity(GlmForCausalLM, hf, cfg)
