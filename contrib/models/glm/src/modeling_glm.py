"""GLM-4 (glm-4-9b-chat-hf architecture) on the TPU framework (contrib port).

≈ reference `contrib/models/glm-4-9b-chat-hf/`. Llama-like decoder with
half-width interleaved-pair partial rotary (partial_rotary_factor), QKV biases,
and a fused gate_up projection in the MLP (split at conversion).
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class GlmInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "num_key_value_heads",
                           "vocab_size", "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("rope_theta", 10000.0), ("rms_norm_eps", 1e-5),
                              ("partial_rotary_factor", 0.5),
                              ("attention_bias", True),
                              ("tie_word_embeddings", False)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


class GlmForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GlmInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        rd = int(config.head_dim * float(config.partial_rotary_factor))
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_bias=bool(config.attention_bias),
            rotary_dim=rd,
            rope_interleaved=True,
            tie_word_embeddings=bool(config.tie_word_embeddings),
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        rd = int(config.head_dim * float(config.partial_rotary_factor))
        return rope_ops.default_inv_freq(rd, float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        I = config.intermediate_size
        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "bq", "bk", "bv",
                                  "wo", "ln2", "wg", "wu", "wd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            gate_up = lin_t(p + "mlp.gate_up_proj.weight")   # (H, 2I)
            layers["wg"].append(gate_up[:, :I])
            layers["wu"].append(gate_up[:, I:])
            layers["wd"].append(lin_t(p + "mlp.down_proj.weight"))
        out = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not config.tie_word_embeddings:
            out["lm_head"] = lin_t("lm_head.weight")
        return out
