"""Janus (DeepSeek Janus-1.3B multimodal) on the TPU framework (contrib port).

≈ reference `contrib/models/Janus-1.3B/src/modeling_janus.py` — which ports the
llama LM backbone only ("text-only version", its line 428). This port EXCEEDS
that scope: the full image-understanding path runs on the shared multimodal
base (runtime/image_to_text.py) — a SigLIP-shaped tower (biased attention with
an optional per-head q/k LayerNorm, erf-GELU MLP, patch conv + learned
positions, final post_layernorm) followed by the depth-2 GELU aligner MLP,
features landing on <image_placeholder> token positions. The VQVAE
image-GENERATION decoder stays out of scope on both sides.
"""

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)
from neuronx_distributed_inference_tpu.ops.vit import ViTSpec, vit_encode
from neuronx_distributed_inference_tpu.runtime.image_to_text import (
    ImageToTextInferenceConfig, TpuModelForImageToText)


def janus_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                        patch_size: int, num_heads: int, eps: float,
                        qk_norm: bool) -> jnp.ndarray:
    """(N, C, H, W) -> (N, T_img, H_text) through the shared ViT + aligner."""
    spec = ViTSpec(patch_size=patch_size, num_heads=num_heads, eps=eps,
                   act="gelu", qk_norm=qk_norm)
    h = vit_encode(vp, pixel_values, spec)
    # aligner: fc1, then (gelu -> linear) per extra depth
    h = h @ vp["align_w1"] + vp["align_b1"]
    for w, b in zip(vp["align_ws"], vp["align_bs"]):
        h = jax.nn.gelu(h, approximate=False) @ w + b
    return h


class JanusInferenceConfig(ImageToTextInferenceConfig, LlamaInferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config",)

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        LlamaInferenceConfig.add_derived_config(self)
        if not hasattr(self, "image_token_index"):
            self.image_token_index = getattr(self, "image_token_id", None)
        if self.image_token_index is None:
            raise ValueError("janus config needs image_token_id")


class JanusForConditionalGeneration(TpuModelForImageToText, LlamaForCausalLM):
    """≈ HF JanusForConditionalGeneration (understanding path)."""

    @classmethod
    def get_config_cls(cls):
        return JanusInferenceConfig

    def vision_encode_fn(self):
        vc = self.config.vision_config
        return functools.partial(
            janus_vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            eps=vc.get("layer_norm_eps", 1e-6),
            qk_norm=bool(vc.get("use_qk_norm", False)),
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k == "lm_head.weight":
                text_sd[k] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        def norm_key(k):
            return k[6:] if k.startswith("model.") else k

        state_dict = {norm_key(k): v for k, v in state_dict.items()}
        vc = config.vision_config
        hidden = vc["hidden_size"]
        qk_norm = bool(vc.get("use_qk_norm", False))

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        keys = ["ln1", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
                "ln2", "ln2_b", "w1", "b1", "w2", "b2"]
        if qk_norm:
            keys += ["q_norm", "q_norm_b", "k_norm", "k_norm_b"]
        layers = {k: [] for k in keys}
        for i in range(vc["num_hidden_layers"]):
            p = f"vision_model.encoder.layers.{i}."
            layers["ln1"].append(get(p + "layer_norm1.weight"))
            layers["ln1_b"].append(get(p + "layer_norm1.bias"))
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.projection_layer.weight"))
            layers["bo"].append(get(p + "self_attn.projection_layer.bias"))
            if qk_norm:
                layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
                layers["q_norm_b"].append(get(p + "self_attn.q_norm.bias"))
                layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
                layers["k_norm_b"].append(get(p + "self_attn.k_norm.bias"))
            layers["ln2"].append(get(p + "layer_norm2.weight"))
            layers["ln2_b"].append(get(p + "layer_norm2.bias"))
            layers["w1"].append(lin_t(p + "mlp.fc1.weight"))
            layers["b1"].append(get(p + "mlp.fc1.bias"))
            layers["w2"].append(lin_t(p + "mlp.fc2.weight"))
            layers["b2"].append(get(p + "mlp.fc2.bias"))

        emb = "vision_model.embeddings."
        conv = get(emb + "patch_embedding.weight")           # (H_vis, C, p, p)
        depth = int(vc.get("depth", 2))
        align_ws, align_bs = [], []
        for i in range(depth - 1):
            align_ws.append(lin_t(f"aligner.hidden_layers.{i}.weight"))
            align_bs.append(get(f"aligner.hidden_layers.{i}.bias"))
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "patch_b": get(emb + "patch_embedding.bias"),
            "pos_embed": get(emb + "position_embedding.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "ln_post": get("vision_model.post_layernorm.weight"),
            "ln_post_b": get("vision_model.post_layernorm.bias"),
            "align_w1": lin_t("aligner.fc1.weight"),
            "align_b1": get("aligner.fc1.bias"),
            "align_ws": align_ws,
            "align_bs": align_bs,
        }
