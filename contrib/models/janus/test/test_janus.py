"""janus parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/janus/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import (  # noqa: F401
    TpuConfig, load_pretrained_config)
from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_janus_generate_matches_hf():
    """Janus understanding path: SigLIP-shaped tower + depth-2 GELU aligner,
    features on <image_placeholder> positions, llama backbone. (The reference
    contrib ports the LM only; the vision path here exceeds it.)"""
    from transformers import (JanusConfig, JanusForConditionalGeneration
                              as HFJanus, JanusVisionConfig, JanusVQVAEConfig,
                              LlamaConfig)

    from contrib.models.janus.src.modeling_janus import (
        JanusForConditionalGeneration)

    vc = JanusVisionConfig(hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=2, image_size=16, patch_size=8,
                           num_channels=3, mlp_ratio=2.0, projection_dim=24,
                           depth=2, use_qk_norm=False, hidden_dropout_rate=0.0,
                           projection_dropout=0.0, attention_dropout=0.0)
    tc = LlamaConfig(vocab_size=256, hidden_size=24, intermediate_size=48,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, rope_theta=10000.0,
                     tie_word_embeddings=False)
    vq = JanusVQVAEConfig(embed_dim=8, num_embeddings=16, base_channels=32,
                          channel_multiplier=[1, 1], num_res_blocks=1,
                          num_hidden_layers=1, hidden_size=32,
                          projection_dim=8, num_patches=4)
    cfg = JanusConfig(vision_config=vc, text_config=tc, vq_config=vq,
                      image_token_id=255, pad_token_id=0)
    torch.manual_seed(0)
    hf = HFJanus(cfg).eval()

    tpu_cfg = TpuConfig(batch_size=2, seq_len=64, max_context_length=32,
                        dtype="float32", context_encoding_buckets=[32],
                        token_generation_buckets=[64])
    config = JanusForConditionalGeneration.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(cfg.to_dict()))
    app = JanusForConditionalGeneration(None, config)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    app.load_vision_from_state_dict(state)

    rng = np.random.default_rng(1)
    ids = rng.integers(1, 250, size=(2, 20))
    ids[:, 2:6] = 255                                   # 4 patches per image
    pixels = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor(ids),
                             pixel_values=torch.tensor(pixels),
                             max_new_tokens=8, do_sample=False,
                             pad_token_id=0, generation_mode="text")
    out = app.generate(ids, pixel_values=pixels, max_new_tokens=8,
                       eos_token_id=-1)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 20:].numpy())
