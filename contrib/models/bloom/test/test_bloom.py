"""bloom parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/bloom/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_bloom_parity():
    from transformers import BloomConfig, BloomForCausalLM as HFBloom

    from contrib.models.bloom.src.modeling_bloom import BloomForCausalLM

    cfg = BloomConfig(vocab_size=256, hidden_size=64, n_layer=2, n_head=4,
                      hidden_dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf = HFBloom(cfg).eval()
    _run_parity(BloomForCausalLM, hf, cfg)
