"""BLOOM on the TPU framework (contrib port).

Exercises: ALiBi attention bias (no positional embeddings), embedding LayerNorm,
per-head-interleaved fused query_key_value split, biased LayerNorm + plain gelu MLP,
tied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs, alibi_slopes
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class BloomInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "n_layer", "n_head", "vocab_size")

    def add_derived_config(self) -> None:
        for attr, default in (("layer_norm_epsilon", 1e-5),):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class BloomForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return BloomInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.n_layer,
            num_heads=config.n_head,
            num_kv_heads=config.n_head,
            head_dim=h // config.n_head,
            intermediate_size=4 * h,
            rms_norm_eps=config.layer_norm_epsilon,
            activation="gelu_pytorch_tanh",       # bloom uses the tanh-approx gelu
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            alibi=True, embed_norm=True,
            tie_word_embeddings=True,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.n_head
        return np.zeros((d // 2,), np.float32)    # ALiBi: no rope

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        h = config.hidden_size
        nh = config.n_head
        d = h // nh

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        for i in range(config.n_layer):
            p = f"transformer.h.{i}."
            # fused QKV, per-head interleave: rows [h0_q, h0_k, h0_v, h1_q, ...]
            qkv = get(p + "self_attention.query_key_value.weight").reshape(
                nh, 3, d, h)
            qkv_b = get(p + "self_attention.query_key_value.bias").reshape(nh, 3, d)
            layers["wq"].append(np.ascontiguousarray(qkv[:, 0].reshape(-1, h).T))
            layers["wk"].append(np.ascontiguousarray(qkv[:, 1].reshape(-1, h).T))
            layers["wv"].append(np.ascontiguousarray(qkv[:, 2].reshape(-1, h).T))
            layers["bq"].append(qkv_b[:, 0].reshape(-1))
            layers["bk"].append(qkv_b[:, 1].reshape(-1))
            layers["bv"].append(qkv_b[:, 2].reshape(-1))
            layers["wo"].append(
                np.ascontiguousarray(get(p + "self_attention.dense.weight").T))
            layers["bo"].append(get(p + "self_attention.dense.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["wg"].append(
                np.ascontiguousarray(get(p + "mlp.dense_h_to_4h.weight").T))
            layers["bg"].append(get(p + "mlp.dense_h_to_4h.bias"))
            layers["wd"].append(
                np.ascontiguousarray(get(p + "mlp.dense_4h_to_h.weight").T))
            layers["bd"].append(get(p + "mlp.dense_4h_to_h.bias"))
        return {
            "embed": get("transformer.word_embeddings.weight"),
            "embed_ln": get("transformer.word_embeddings_layernorm.weight"),
            "embed_ln_b": get("transformer.word_embeddings_layernorm.bias"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.ln_f.weight"),
            "final_norm_b": get("transformer.ln_f.bias"),
            "alibi_slopes": alibi_slopes(nh),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
