"""GraniteMoeShared (IBM granite-4.0-tiny style) on the TPU framework
(contrib port).

≈ reference contrib granite family. GraniteMoe (granite multiplier quartet +
topk_softmax-routed fused-projection MoE) plus a DENSE shared expert added to
every MoE output — ungated, unlike qwen2-moe's sigmoid-gated shared expert
(HF `GraniteMoeSharedDecoderLayer`: `moe_out + shared_mlp(hn)`), riding
``MoEArgs.shared_expert_gated=False``.
"""

import dataclasses
from typing import Dict

import numpy as np

from contrib.models.granitemoe.src.modeling_granitemoe import (
    GraniteMoeForCausalLM, GraniteMoeInferenceConfig)
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs


class GraniteMoeSharedInferenceConfig(GraniteMoeInferenceConfig):
    def add_derived_config(self) -> None:
        super().add_derived_config()
        if not hasattr(self, "shared_intermediate_size") or \
                self.shared_intermediate_size is None:
            self.shared_intermediate_size = 0


class GraniteMoeSharedForCausalLM(GraniteMoeForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return GraniteMoeSharedInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        args = super().arch_args_from_config(config)
        moe = dataclasses.replace(
            args.moe,
            shared_expert_intermediate_size=int(config.shared_intermediate_size),
            shared_expert_gated=False)
        return dataclasses.replace(args, moe=moe)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        out = super().convert_hf_state_dict(state_dict, config)
        if not config.shared_intermediate_size:
            return out
        si = config.shared_intermediate_size
        wg, wu, wd = [], [], []
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}.shared_mlp."
            fused = np.asarray(state_dict[p + "input_linear.weight"])  # (2S, H)
            wg.append(np.ascontiguousarray(fused[:si, :].T))
            wu.append(np.ascontiguousarray(fused[si:, :].T))
            wd.append(np.ascontiguousarray(
                np.asarray(state_dict[p + "output_linear.weight"]).T))
        out["layers"]["shared_wg"] = np.stack(wg)
        out["layers"]["shared_wu"] = np.stack(wu)
        out["layers"]["shared_wd"] = np.stack(wd)
        return out
