"""granitemoeshared parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/granitemoeshared/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_granitemoeshared_parity():
    """GraniteMoeShared: granitemoe plus an ungated dense shared expert summed
    with every routed-MoE output."""
    from transformers import (GraniteMoeSharedConfig,
                              GraniteMoeSharedForCausalLM as HFGms)

    from contrib.models.granitemoeshared.src.modeling_granitemoeshared import (
        GraniteMoeSharedForCausalLM)

    cfg = GraniteMoeSharedConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        shared_intermediate_size=80, num_local_experts=4,
        num_experts_per_tok=2, embedding_multiplier=2.0,
        attention_multiplier=0.3, residual_multiplier=0.8,
        logits_scaling=1.5, attention_bias=False, rope_theta=10000.0,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(0)
    hf = HFGms(cfg).eval()
    _run_parity(GraniteMoeSharedForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
