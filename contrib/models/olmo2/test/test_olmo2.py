"""olmo2 parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/olmo2/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_olmo2_parity():
    from transformers import Olmo2Config, Olmo2ForCausalLM as HFOlmo2

    from contrib.models.olmo2.src.modeling_olmo2 import Olmo2ForCausalLM

    cfg = Olmo2Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, pad_token_id=0,
                      tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFOlmo2(cfg).eval()
    _run_parity(Olmo2ForCausalLM, hf, cfg)
