"""granite parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/granite/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_granite_parity():
    from transformers import GraniteConfig, GraniteForCausalLM as HFGranite

    from contrib.models.granite.src.modeling_granite import GraniteForCausalLM

    cfg = GraniteConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, embedding_multiplier=12.0,
                        attention_multiplier=0.015625, residual_multiplier=0.22,
                        logits_scaling=16.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFGranite(cfg).eval()
    _run_parity(GraniteForCausalLM, hf, cfg)
