"""phi parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/phi/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_phi_parity():
    from transformers import PhiConfig, PhiForCausalLM as HFPhi

    from contrib.models.phi.src.modeling_phi import PhiForCausalLM

    cfg = PhiConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    partial_rotary_factor=0.5, max_position_embeddings=128,
                    hidden_act="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
                    attention_dropout=0.0, qk_layernorm=False)
    torch.manual_seed(0)
    hf = HFPhi(cfg).eval()
    _run_parity(PhiForCausalLM, hf, cfg)
