"""Phi-1.5 / Phi-2 on the TPU framework (contrib port, ≈ reference
`contrib/models/phi-1_5/`).

Exercises: partial rotary, parallel residual with a SHARED input LayerNorm, biased
projections everywhere, plain gelu MLP, biased untied output head.
"""

from typing import Dict

import numpy as np

from neuronx_distributed_inference_tpu.config import InferenceConfig
from neuronx_distributed_inference_tpu.models.base import ModelArchArgs
from neuronx_distributed_inference_tpu.ops import rope as rope_ops
from neuronx_distributed_inference_tpu.runtime.application import (
    TpuModelForCausalLM)


class PhiInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("hidden_size", "num_hidden_layers",
                           "num_attention_heads", "vocab_size",
                           "intermediate_size")

    def add_derived_config(self) -> None:
        for attr, default in (("partial_rotary_factor", 0.5),
                              ("rope_theta", 10000.0),
                              ("layer_norm_eps", 1e-5),
                              ("hidden_act", "gelu_new"),
                              ("num_key_value_heads", None)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads


class PhiForCausalLM(TpuModelForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return PhiInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        h = config.hidden_size
        d = h // config.num_attention_heads
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=h,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            head_dim=d,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.layer_norm_eps,
            activation=config.hidden_act,
            norm_type="layer", norm_bias=True,
            mlp_kind="plain", mlp_bias=True,
            attention_bias=True, o_bias=True,
            parallel_residual=True, shared_ln=True,  # one ln feeds attn AND mlp
            rotary_dim=int(d * config.partial_rotary_factor),
        )

    def logical_axes(self) -> Dict:
        from neuronx_distributed_inference_tpu.models import base as model_base

        axes = model_base.param_logical_axes(self.arch_args)
        axes["lm_head_b"] = ("vocab",)
        return axes

    def init_random_params(self, key) -> Dict:
        import jax.numpy as jnp

        params = super().init_random_params(key)
        params["lm_head_b"] = jnp.zeros((self.arch_args.vocab_size,),
                                        self.tpu_config.jax_dtype)
        return params

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        d = config.hidden_size // config.num_attention_heads
        return rope_ops.default_inv_freq(int(d * config.partial_rotary_factor),
                                         float(config.rope_theta))

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_b", "wq", "wk", "wv", "bq", "bk",
                                  "bv", "wo", "bo", "ln2", "ln2_b", "wg", "bg",
                                  "wd", "bd")}
        for i in range(config.num_hidden_layers):
            p = f"model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.dense.weight"))
            layers["bo"].append(get(p + "self_attn.dense.bias"))
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            # shared_ln: ln2 unused but kept for layout uniformity
            layers["ln2"].append(np.ones_like(get(p + "input_layernorm.weight")))
            layers["ln2_b"].append(np.zeros_like(get(p + "input_layernorm.bias")))
            layers["wg"].append(lin_t(p + "mlp.fc1.weight"))
            layers["bg"].append(get(p + "mlp.fc1.bias"))
            layers["wd"].append(lin_t(p + "mlp.fc2.weight"))
            layers["bd"].append(get(p + "mlp.fc2.bias"))
        return {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.final_layernorm.weight"),
            "final_norm_b": get("model.final_layernorm.bias"),
            "lm_head": lin_t("lm_head.weight"),
            "lm_head_b": get("lm_head.bias"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
