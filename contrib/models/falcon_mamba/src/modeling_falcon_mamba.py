"""FalconMamba (TII pure-SSM) on the TPU framework (contrib port).

≈ reference contrib falcon family. Identical to mamba (selective SSM,
associative-scan prefill, fp32 state + conv-tail cache) except a WEIGHTLESS
RMSNorm (`FalconMambaMixer.rms_forward`, eps=`mixer_rms_eps`) is applied to
the dt/B/C splits of x_proj before the recurrence — wired through
``MambaArchArgs.mixer_rms_eps``. Checkpoint layout matches mamba's
(`backbone.layers.{i}.mixer.*`), so conversion is inherited unchanged.
"""

from contrib.models.mamba.src.modeling_mamba import (MambaArchArgs,
                                                     MambaForCausalLM,
                                                     MambaInferenceConfig)


class FalconMambaInferenceConfig(MambaInferenceConfig):
    def add_derived_config(self) -> None:
        super().add_derived_config()
        if not hasattr(self, "mixer_rms_eps") or self.mixer_rms_eps is None:
            self.mixer_rms_eps = 1e-6


class FalconMambaForCausalLM(MambaForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return FalconMambaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> MambaArchArgs:
        import dataclasses
        return dataclasses.replace(super().arch_args_from_config(config),
                                   mixer_rms_eps=float(config.mixer_rms_eps))
