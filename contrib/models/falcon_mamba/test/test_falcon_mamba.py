"""falcon_mamba parity tests (reference contrib shape: README.md + src/ + test/ per family).

Moved from the former central tests/test_contrib_models.py; executed both directly
(`pytest contrib/models/falcon_mamba/test/`) and through the tests/test_contrib_models.py
aggregator (the CI gate).
"""


import pytest
import torch

from contrib.models._test_harness import *  # noqa: F401,F403

pytestmark = pytest.mark.slow


def test_falcon_mamba_parity():
    """FalconMamba: mamba with a weightless RMSNorm over the dt/B/C x_proj
    splits (mixer_rms_eps)."""
    from transformers import (FalconMambaConfig,
                              FalconMambaForCausalLM as HFFalconMamba)

    from contrib.models.falcon_mamba.src.modeling_falcon_mamba import (
        FalconMambaForCausalLM)

    cfg = FalconMambaConfig(vocab_size=256, hidden_size=32, state_size=8,
                            num_hidden_layers=2, conv_kernel=4, expand=2,
                            time_step_rank=4, use_bias=False,
                            use_conv_bias=True, mixer_rms_eps=1e-6,
                            pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = HFFalconMamba(cfg).eval()
    _run_parity(FalconMambaForCausalLM, hf, cfg, atol=2e-3, rtol=1e-3)
