"""Direct-run configuration for contrib family tests
(`pytest contrib/models/<fam>/test/`): the same virtual 8-device CPU mesh as
tests/conftest.py, so family parity runs never require TPU hardware."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
