"""Direct-run configuration for contrib family tests
(`pytest contrib/models/<fam>/test/`): the same virtual 8-device CPU mesh as
tests/conftest.py via the shared repo-root bootstrap."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _tpu_test_bootstrap  # noqa: F401,E402  (side effect: CPU mesh)
