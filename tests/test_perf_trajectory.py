"""scripts/perf_trajectory.py: provenance-grouped trajectory + regression
gate (ISSUE-14), over synthetic snapshot fixtures AND the committed tree.

The checker's whole job is to keep CPU-container numbers from masquerading
as the TPU trajectory: mixed-provenance snapshots must land in distinct
groups, absolute keys must only gate inside verified groups, analytic
bytes-per-step canaries must gate everywhere, and a malformed snapshot must
be a loud error, never a silently skipped file."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mod():
    spec = importlib.util.spec_from_file_location(
        "perf_trajectory", os.path.join(REPO, "scripts",
                                        "perf_trajectory.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


TPU_PROV = {"schema": "tpu-inference-provenance/1", "key": "tpu-v5e",
            "verified": True, "capture": "driver-captured"}
CPU_PROV = {"schema": "tpu-inference-provenance/1", "key": "cpu-container",
            "verified": False, "capture": "local"}


def _bench_snap(path, n, prov, extra, value=1000.0):
    line = {"metric": "m", "value": value, "unit": "tokens/s",
            "vs_baseline": value / 2000.0, "extra": extra}
    with open(path, "w") as fh:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "provenance": prov,
                   "tail": json.dumps(line) + "\n", "parsed": line}, fh)


def _write_series(d, rounds):
    """rounds: [(n, prov, value, extra)] -> BENCH_rNN.json files."""
    for n, prov, value, extra in rounds:
        _bench_snap(str(d / f"BENCH_r{n:02d}.json"), n, prov, extra, value)


# ------------------------------------------------------------------ grouping
def test_mixed_provenance_snapshots_group_separately(tmp_path):
    mod = _mod()
    _write_series(tmp_path, [
        (1, TPU_PROV, 1000.0, {"streamed_bytes_per_step_gb": 8.0}),
        (2, TPU_PROV, 1200.0, {"streamed_bytes_per_step_gb": 8.0}),
        (3, CPU_PROV, 2.5, {"streamed_bytes_per_step_gb": 8.0}),
    ])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    assert set(groups) == {("bench", "tpu-v5e"), ("bench", "cpu-container")}
    assert [s.round for s in groups[("bench", "tpu-v5e")]] == [1, 2]
    assert [s.round for s in groups[("bench", "cpu-container")]] == [3]
    # the real committed tree groups the same way (acceptance bar): r1-r5
    # TPU vs r6-r7 CPU, both bench and multichip families
    real = mod.group_snapshots(mod.load_all(REPO))
    assert [s.round for s in real[("bench", "tpu-v5e")]] == [1, 2, 3, 4, 5]
    assert [s.round for s in real[("bench", "cpu-container")]] == [6, 7]
    assert ("multichip", "tpu-v5e") in real
    assert ("multichip", "cpu-container") in real


def test_unstamped_snapshot_quarantines_as_unknown(tmp_path):
    mod = _mod()
    line = {"metric": "m", "value": 5.0, "unit": "tokens/s", "extra": {}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump({"n": 1, "rc": 0, "tail": json.dumps(line),
                   "parsed": line}, fh)
    s = mod.load_snapshot(str(tmp_path / "BENCH_r01.json"))
    assert s.key == "unknown" and not s.verified
    assert any("provenance" in n for n in s.notes)


# ------------------------------------------------------------- regression gate
def test_absolute_regression_gates_only_verified_groups(tmp_path):
    mod = _mod()
    # a 10x tok/s collapse: fails in the TPU group...
    _write_series(tmp_path, [(1, TPU_PROV, 5000.0, {}),
                             (2, TPU_PROV, 500.0, {})])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    regs = mod.check_regressions(groups[("bench", "tpu-v5e")])
    assert any(r["key"] == "value" for r in regs)
    # ...but NOT in a cpu-container group (different boxes differ ~6x;
    # absolute numbers there are not the trajectory)
    for f in tmp_path.glob("*.json"):
        f.unlink()
    _write_series(tmp_path, [(6, CPU_PROV, 5000.0, {}),
                             (7, CPU_PROV, 500.0, {})])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    assert mod.check_regressions(groups[("bench", "cpu-container")]) == []


def test_analytic_bytes_canary_gates_every_provenance(tmp_path):
    """The ROADMAP item-4 bytes-per-step canary: a byte-model increase past
    5% fails even on the CPU container; a decrease (an optimization) and
    within-tolerance noise pass."""
    mod = _mod()
    _write_series(tmp_path, [
        (6, CPU_PROV, 10.0, {"streamed_bytes_per_step_gb": 2.52}),
        (7, CPU_PROV, 10.0, {"streamed_bytes_per_step_gb": 3.10}),
    ])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    regs = mod.check_regressions(groups[("bench", "cpu-container")])
    assert [r["key"] for r in regs] == ["streamed_bytes_per_step_gb"]
    assert regs[0]["rounds"] == [6, 7]
    # decrease passes (r4->r5 int4 halved the stream on the real tree)
    for f in tmp_path.glob("*.json"):
        f.unlink()
    _write_series(tmp_path, [
        (6, CPU_PROV, 10.0, {"streamed_bytes_per_step_gb": 8.31}),
        (7, CPU_PROV, 10.0, {"streamed_bytes_per_step_gb": 5.76}),
    ])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    assert mod.check_regressions(groups[("bench", "cpu-container")]) == []


def test_ratio_tolerance_and_missing_keys(tmp_path):
    mod = _mod()
    _write_series(tmp_path, [
        # paged_vs_dense 0.70 -> 0.62: -11% < 15% tolerance, passes; the
        # megastep ratio only exists in r7 (new key — cannot regress)
        (6, CPU_PROV, 10.0, {"paged_vs_dense": 0.70}),
        (7, CPU_PROV, 10.0, {"paged_vs_dense": 0.62,
                             "megastep_speedup_vs_stepwise": 7.2}),
    ])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    assert mod.check_regressions(groups[("bench", "cpu-container")]) == []
    # past tolerance it fails in ANY provenance group
    for f in tmp_path.glob("*.json"):
        f.unlink()
    _write_series(tmp_path, [
        (6, CPU_PROV, 10.0, {"paged_vs_dense": 0.70}),
        (7, CPU_PROV, 10.0, {"paged_vs_dense": 0.40}),
    ])
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    regs = mod.check_regressions(groups[("bench", "cpu-container")])
    assert [r["key"] for r in regs] == ["paged_vs_dense"]


# ------------------------------------------------------------ CLI / exit codes
def _run_cli(args):
    return subprocess.run([sys.executable,
                           os.path.join(REPO, "scripts",
                                        "perf_trajectory.py")] + args,
                          capture_output=True, text=True)


def test_ci_exit_codes(tmp_path):
    # clean series -> 0
    _write_series(tmp_path, [(1, TPU_PROV, 1000.0, {}),
                             (2, TPU_PROV, 1100.0, {})])
    r = _run_cli(["--dir", str(tmp_path), "--ci"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TRAJECTORY OK" in r.stdout
    # regressed series -> 1 under --ci, 0 (reported) without
    _write_series(tmp_path, [(3, TPU_PROV, 100.0, {})])
    r = _run_cli(["--dir", str(tmp_path), "--ci"])
    assert r.returncode == 1
    assert "REGRESSION" in r.stdout
    assert _run_cli(["--dir", str(tmp_path)]).returncode == 0


def test_malformed_snapshot_errors(tmp_path):
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        fh.write('{"n": 1, "tail": TRUNCATED')
    r = _run_cli(["--dir", str(tmp_path), "--ci"])
    assert r.returncode == 2
    assert "ERROR" in r.stderr
    # an empty directory is an error too (a gate over nothing is vacuous)
    empty = tmp_path / "empty"
    empty.mkdir()
    assert _run_cli(["--dir", str(empty), "--ci"]).returncode == 2


def test_ci_passes_on_the_committed_tree_and_writes_json(tmp_path):
    """The acceptance bar: the committed r1-r7 snapshots run clean, report
    the two provenance series, and --ci exits 0."""
    out = str(tmp_path / "report.json")
    r = _run_cli(["--ci", "--json", out])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "bench :: tpu-v5e (verified)" in r.stdout
    assert "bench :: cpu-container (unverified)" in r.stdout
    rep = json.load(open(out))
    assert rep["regressions"] == []
    assert "bench::tpu-v5e" in rep["groups"]
    assert "multichip::cpu-container" in rep["groups"]


def test_multichip_ok_verdict_gated(tmp_path):
    mod = _mod()
    for n, ok in ((1, True), (2, False)):
        with open(tmp_path / f"MULTICHIP_r{n:02d}.json", "w") as fh:
            json.dump({"n_devices": 8, "rc": 0 if ok else 1, "ok": ok,
                       "provenance": CPU_PROV, "tail": ""}, fh)
    groups = mod.group_snapshots(mod.load_all(str(tmp_path)))
    regs = mod.check_regressions(groups[("multichip", "cpu-container")])
    assert [r["key"] for r in regs] == ["ok"]
