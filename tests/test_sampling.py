"""On-device sampling tests (≈ reference `test/unit/modules/generation/test_sampling.py`)."""

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import OnDeviceSamplingConfig
from neuronx_distributed_inference_tpu.ops import sampling as S


def _logits(batch=4, vocab=100):
    return jnp.asarray(np.random.randn(batch, vocab).astype(np.float32) * 3)


def test_prepare_sampling_params_broadcast():
    p = S.prepare_sampling_params(3, top_k=1, top_p=0.9, temperature=[1.0, 0.5, 2.0])
    assert p.shape == (3, 3)
    np.testing.assert_allclose(p[:, 0], 1.0)
    np.testing.assert_allclose(p[:, 2], [1.0, 0.5, 2.0])


def test_greedy_matches_argmax():
    logits = _logits()
    cfg = OnDeviceSamplingConfig(dynamic=False)
    tokens = S.sample(logits, jnp.asarray(S.prepare_sampling_params(4)), None, cfg)
    np.testing.assert_array_equal(np.asarray(tokens), np.argmax(np.asarray(logits), -1))


def test_dynamic_greedy_rows_exact_even_with_key():
    logits = _logits()
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(4, top_k=[1, 1, 50, 50], top_p=1.0,
                                       temperature=1.0)
    tokens = S.sample(logits, jnp.asarray(params), jax.random.PRNGKey(0), cfg)
    argmax = np.argmax(np.asarray(logits), -1)
    np.testing.assert_array_equal(np.asarray(tokens)[:2], argmax[:2])


def test_top_k_restricts_support():
    logits = _logits(batch=64, vocab=50)
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(64, top_k=5, top_p=1.0, temperature=2.0)
    top5 = np.argsort(-np.asarray(logits), axis=-1)[:, :5]
    for seed in range(5):
        tokens = np.asarray(S.sample(logits, jnp.asarray(params),
                                     jax.random.PRNGKey(seed), cfg))
        for b in range(64):
            assert tokens[b] in top5[b]


def test_top_p_restricts_support():
    # peaked distribution: top-p=0.9 keeps only the high-prob head
    base = np.full((8, 50), -10.0, dtype=np.float32)
    base[:, 0] = 5.0
    base[:, 1] = 4.0
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(8, top_k=50, top_p=0.9, temperature=1.0)
    for seed in range(5):
        tokens = np.asarray(S.sample(jnp.asarray(base), jnp.asarray(params),
                                     jax.random.PRNGKey(seed), cfg))
        assert set(tokens.tolist()) <= {0, 1}


def test_temperature_flattens_distribution():
    base = np.zeros((512, 4), dtype=np.float32)
    base[:, 0] = 2.0
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    cold = S.prepare_sampling_params(512, top_k=4, top_p=1.0, temperature=0.25)
    hot = S.prepare_sampling_params(512, top_k=4, top_p=1.0, temperature=4.0)
    t_cold = np.asarray(S.sample(jnp.asarray(base), jnp.asarray(cold),
                                 jax.random.PRNGKey(1), cfg))
    t_hot = np.asarray(S.sample(jnp.asarray(base), jnp.asarray(hot),
                                jax.random.PRNGKey(1), cfg))
    assert (t_cold == 0).mean() > (t_hot == 0).mean()


def test_deterministic_same_key_same_tokens():
    logits = _logits()
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(4, top_k=50, top_p=0.95, temperature=1.0)
    a = S.sample(logits, jnp.asarray(params), jax.random.PRNGKey(7), cfg)
    b = S.sample(logits, jnp.asarray(params), jax.random.PRNGKey(7), cfg)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
