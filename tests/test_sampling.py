"""On-device sampling tests (≈ reference `test/unit/modules/generation/test_sampling.py`)."""

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import OnDeviceSamplingConfig
from neuronx_distributed_inference_tpu.ops import sampling as S


def _logits(batch=4, vocab=100):
    return jnp.asarray(np.random.randn(batch, vocab).astype(np.float32) * 3)


def test_prepare_sampling_params_broadcast():
    p = S.prepare_sampling_params(3, top_k=1, top_p=0.9, temperature=[1.0, 0.5, 2.0])
    assert p.shape == (3, 3)
    np.testing.assert_allclose(p[:, 0], 1.0)
    np.testing.assert_allclose(p[:, 2], [1.0, 0.5, 2.0])


def test_greedy_matches_argmax():
    logits = _logits()
    cfg = OnDeviceSamplingConfig(dynamic=False)
    tokens = S.sample(logits, jnp.asarray(S.prepare_sampling_params(4)), None, cfg)
    np.testing.assert_array_equal(np.asarray(tokens), np.argmax(np.asarray(logits), -1))


def test_dynamic_greedy_rows_exact_even_with_key():
    logits = _logits()
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(4, top_k=[1, 1, 50, 50], top_p=1.0,
                                       temperature=1.0)
    tokens = S.sample(logits, jnp.asarray(params), jax.random.PRNGKey(0), cfg)
    argmax = np.argmax(np.asarray(logits), -1)
    np.testing.assert_array_equal(np.asarray(tokens)[:2], argmax[:2])


def test_top_k_restricts_support():
    logits = _logits(batch=64, vocab=50)
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(64, top_k=5, top_p=1.0, temperature=2.0)
    top5 = np.argsort(-np.asarray(logits), axis=-1)[:, :5]
    for seed in range(5):
        tokens = np.asarray(S.sample(logits, jnp.asarray(params),
                                     jax.random.PRNGKey(seed), cfg))
        for b in range(64):
            assert tokens[b] in top5[b]


def test_top_p_restricts_support():
    # peaked distribution: top-p=0.9 keeps only the high-prob head
    base = np.full((8, 50), -10.0, dtype=np.float32)
    base[:, 0] = 5.0
    base[:, 1] = 4.0
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(8, top_k=50, top_p=0.9, temperature=1.0)
    for seed in range(5):
        tokens = np.asarray(S.sample(jnp.asarray(base), jnp.asarray(params),
                                     jax.random.PRNGKey(seed), cfg))
        assert set(tokens.tolist()) <= {0, 1}


def test_temperature_flattens_distribution():
    base = np.zeros((512, 4), dtype=np.float32)
    base[:, 0] = 2.0
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    cold = S.prepare_sampling_params(512, top_k=4, top_p=1.0, temperature=0.25)
    hot = S.prepare_sampling_params(512, top_k=4, top_p=1.0, temperature=4.0)
    t_cold = np.asarray(S.sample(jnp.asarray(base), jnp.asarray(cold),
                                 jax.random.PRNGKey(1), cfg))
    t_hot = np.asarray(S.sample(jnp.asarray(base), jnp.asarray(hot),
                                jax.random.PRNGKey(1), cfg))
    assert (t_cold == 0).mean() > (t_hot == 0).mean()


def test_deterministic_same_key_same_tokens():
    logits = _logits()
    cfg = OnDeviceSamplingConfig(dynamic=True, do_sample=True)
    params = S.prepare_sampling_params(4, top_k=50, top_p=0.95, temperature=1.0)
    a = S.sample(logits, jnp.asarray(params), jax.random.PRNGKey(7), cfg)
    b = S.sample(logits, jnp.asarray(params), jax.random.PRNGKey(7), cfg)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_sharded_sampling_matches_single_device(tiny_llama_hf_config):
    """DataParallelSampler analog (≈ reference `sampling.py:469-569`): under a
    dp-sharded mesh the on-device sampler runs batch-parallel via GSPMD — the
    same seed must commit exactly the same tokens as the unsharded mesh, for
    greedy AND stochastic sampling."""
    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs 2 virtual devices")

    def build(dp):
        cfg = TpuConfig(batch_size=4, seq_len=64, max_context_length=32,
                        dtype="float32", dp_degree=dp,
                        is_continuous_batching=dp > 1,
                        context_encoding_buckets=[16, 32],
                        token_generation_buckets=[32, 64],
                        on_device_sampling_config=OnDeviceSamplingConfig(
                            do_sample=True, top_k=8, top_p=0.9,
                            temperature=0.8))
        config = LlamaInferenceConfig(
            cfg, load_config=load_pretrained_config(tiny_llama_hf_config))
        app = LlamaForCausalLM(None, config)
        app.load_random(seed=0)
        return app

    rng = np.random.default_rng(0)
    ids = rng.integers(1, 256, size=(4, 10)).astype(np.int32)
    out1 = build(1).generate(ids, max_new_tokens=8, seed=7)
    out2 = build(2).generate(ids, max_new_tokens=8, seed=7)
    np.testing.assert_array_equal(out1.tokens, out2.tokens)

    sp = S.prepare_sampling_params(4)           # greedy rows via dynamic params
    outg1 = build(1).generate(ids, max_new_tokens=8, sampling_params=sp)
    outg2 = build(2).generate(ids, max_new_tokens=8, sampling_params=sp)
    np.testing.assert_array_equal(outg1.tokens, outg2.tokens)
