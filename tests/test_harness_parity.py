"""Harness-parity tests (VERDICT r3 #6).

Covers the three reference harness features closed in round 4:

- per-submodel latency breakdown in the benchmark harness
  (≈ reference `utils/benchmark.py:380-429` forward-hook collectors);
- draft-logit capture + matching for speculative decoding
  (≈ reference `utils/accuracy.py:1214` `run_accuracy_draft_logit_test_flow`);
- chunked-prefill generation loop producing logits for accuracy comparison
  (≈ reference `utils/accuracy.py:940` `generate_with_chunked_prefill`).
"""

import numpy as np
import pytest

from neuronx_distributed_inference_tpu.config import (
    OnDeviceSamplingConfig, TpuConfig, load_pretrained_config)
from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)
from neuronx_distributed_inference_tpu.runtime.speculation import (
    FusedSpeculativeModel)
from neuronx_distributed_inference_tpu.utils import accuracy, benchmark

pytestmark = pytest.mark.slow  # heavy e2e: excluded from the fast gate


def _make_app(hf_cfg, seed=0, batch=2, **cfg_kw):
    tpu_cfg = TpuConfig(
        batch_size=batch, seq_len=128, max_context_length=32, dtype="float32",
        context_encoding_buckets=[16, 32], token_generation_buckets=[64, 128],
        on_device_sampling_config=OnDeviceSamplingConfig(do_sample=False),
        **cfg_kw)
    config = LlamaInferenceConfig(tpu_cfg, load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=seed)
    return app


def test_submodel_latency_breakdown(tiny_llama_hf_config):
    app = _make_app(tiny_llama_hf_config)
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 256, size=(2, 12)).astype(np.int32)
    report = benchmark.benchmark_sampling(app, ids, max_new_tokens=12, n_runs=2,
                                          warmup_runs=1)
    subs = report.extra["submodels"]
    assert benchmark.CONTEXT_ENCODING_MODEL in subs
    assert benchmark.TOKEN_GENERATION_MODEL in subs
    for rep in subs.values():
        assert rep["latency_ms_p50"] > 0
    # outside a collection scope, recording must be a no-op
    benchmark.record_submodel(benchmark.CONTEXT_ENCODING_MODEL, 1.0)


def test_submodel_breakdown_speculation(tiny_llama_hf_config):
    target = _make_app(tiny_llama_hf_config, seed=0)
    draft = _make_app(tiny_llama_hf_config, seed=0)
    spec = FusedSpeculativeModel(target, draft, speculation_length=3, greedy=True)
    ids = np.random.default_rng(0).integers(1, 256, size=(2, 8)).astype(np.int32)
    with benchmark.submodel_collection() as collectors:
        spec.generate(ids, max_new_tokens=10)
    assert benchmark.SPECULATION_MODEL in collectors
    assert len(collectors[benchmark.SPECULATION_MODEL].samples_s) >= 1


def test_draft_logit_capture_and_matching(tiny_llama_hf_config, tmp_path):
    target = _make_app(tiny_llama_hf_config, seed=0)
    draft = _make_app(tiny_llama_hf_config, seed=0)
    spec = FusedSpeculativeModel(target, draft, speculation_length=3, greedy=True)
    ids = np.random.default_rng(1).integers(1, 256, size=(2, 8)).astype(np.int32)
    out = spec.generate(ids, max_new_tokens=12, capture_draft_logits=True)
    assert out.draft_logits, "capture returned no draft loops"
    b, km1, v = out.draft_logits[0].shape
    assert (b, km1, v) == (2, 2, 256)

    # self-match passes; golden dir round-trips
    golden_dir = str(tmp_path / "goldens")
    accuracy.save_draft_goldens(golden_dir, out.draft_logits)
    loaded = accuracy.load_draft_goldens(golden_dir)
    assert len(loaded) == len(out.draft_logits)
    report = accuracy.check_accuracy_draft_logits(out.draft_logits, loaded)
    assert report.passed and report.first_failure is None

    # a perturbed golden fails with the failing (loop, iter) reported
    bad = [a.copy() for a in loaded]
    bad[0][:, 0] += 1.0
    report = accuracy.check_accuracy_draft_logits(out.draft_logits, bad)
    assert not report.passed
    assert report.first_failure == (0, 0)

    # one-call flow against the golden dir (fresh generate, deterministic greedy)
    report = accuracy.check_draft_accuracy_vs_reference(
        spec, golden_dir, ids, max_new_tokens=12)
    assert report.passed


def test_chunked_prefill_matches_straight_path(tiny_llama_hf_config):
    """Chunked prefill through the paged path must logit-match the dense
    straight-through prefill (fp32 CPU: tight tolerance)."""
    paged = _make_app(tiny_llama_hf_config, batch=2,
                      is_continuous_batching=True, paged_attention_enabled=True,
                      pa_num_blocks=48, pa_block_size=8)
    dense = _make_app(tiny_llama_hf_config, batch=2)
    rng = np.random.default_rng(2)
    ids = rng.integers(1, 256, size=(2, 24)).astype(np.int32)

    tokens, logits = accuracy.generate_with_chunked_prefill(
        paged, ids, max_new_tokens=8, chunk_size=16)
    ref = dense.generate(ids, max_new_tokens=8, return_logits=True)

    assert tokens.shape == (2, 8)
    np.testing.assert_array_equal(tokens, ref.tokens)
    rep = accuracy.check_logit_accuracy(logits, ref.logits,
                                        divergence_difference_tol=2e-4)
    assert rep.passed, f"max err {rep.max_abs_error}"
