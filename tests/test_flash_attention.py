"""Pallas flash-attention parity vs the jnp reference (interpret mode on CPU).

≈ reference kernel-vs-native parity tests (`utils/testing.py:67-120` pattern applied to
the NKI attention kernels).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_inference_tpu.ops import attention as attn_ops
from neuronx_distributed_inference_tpu.ops.flash_attention import flash_attention


def _ref_attention(q, k, v, causal=True, q_offset=0, window=None, scale=None):
    sq, skv = q.shape[2], k.shape[2]
    if window is not None:
        mask = attn_ops.sliding_window_mask(sq, skv, window, q_offset=q_offset)
    else:
        mask = attn_ops.causal_mask(sq, skv, q_offset=q_offset)
    with jax.default_matmul_precision("highest"):
        return attn_ops.attend(q, k, v, mask=mask[None, None], scale=scale)


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape, dtype=np.float32))


@pytest.mark.parametrize("sq,skv,block", [(256, 256, 128), (128, 128, 64),
                                          (384, 384, 128)])
def test_flash_matches_reference_causal(sq, skv, block):
    b, hq, hkv, d = 2, 4, 2, 64
    q, k, v = _rand((b, hq, sq, d), 1), _rand((b, hkv, skv, d), 2), _rand(
        (b, hkv, skv, d), 3)
    got = flash_attention(q, k, v, causal=True, block_q=block, block_k=block,
                          interpret=True)
    want = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_flash_unaligned_seq_padding():
    b, hq, hkv, d = 1, 2, 1, 32
    q, k, v = _rand((b, hq, 200, d), 4), _rand((b, hkv, 200, d), 5), _rand(
        (b, hkv, 200, d), 6)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    want = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_flash_q_offset_cp_trapezoid():
    """q rows are a CP shard starting at absolute position 128 over the full kv."""
    b, hq, hkv, d = 1, 2, 2, 32
    full_q = _rand((b, hq, 256, d), 7)
    k, v = _rand((b, hkv, 256, d), 8), _rand((b, hkv, 256, d), 9)
    shard_q = full_q[:, :, 128:, :]
    got = flash_attention(shard_q, k, v, causal=True, q_offset=128, interpret=True)
    want = _ref_attention(full_q, k, v, causal=True)[:, :, 128:, :]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_flash_sliding_window():
    b, hq, hkv, d = 1, 2, 1, 32
    q, k, v = _rand((b, hq, 256, d), 10), _rand((b, hkv, 256, d), 11), _rand(
        (b, hkv, 256, d), 12)
    got = flash_attention(q, k, v, causal=True, window=64, block_q=64, block_k=64,
                          interpret=True)
    want = _ref_attention(q, k, v, causal=True, window=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_flash_bf16_reasonable():
    b, hq, hkv, d = 1, 4, 2, 64
    q = _rand((b, hq, 256, d), 13).astype(jnp.bfloat16)
    k = _rand((b, hkv, 256, d), 14).astype(jnp.bfloat16)
    v = _rand((b, hkv, 256, d), 15).astype(jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _ref_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32), np.asarray(want),
                               atol=0.03, rtol=0.05)
