"""Device-resident serving megasteps (ISSUE-10 / ROADMAP open item 2): the
``lax.while_loop`` serving loop must produce BIT-IDENTICAL tokens to the
step-wise path across the whole exactness matrix — K in {1, 4, 16} x
async_depth in {1, 2}, including mid-loop eos, in-loop block consumption up
to the host-pre-reserved budget followed by a ``blocks`` early-exit, the
emitted-ring wrap service exit, the pending-arrival service flag, and
spec-chunk / mixed-step composition through the ONE guarded fall-through —
while the device telemetry carry's per-inner-step counters keep matching the
host's event-log recompute exactly at every pipeline flush.
"""

import numpy as np
import pytest

from neuronx_distributed_inference_tpu.config import (
    OnDeviceSamplingConfig, TpuConfig, load_pretrained_config)
from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)
from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
    ContinuousBatchingRunner)


def _make_app(hf_cfg, paged=True, slots=2, blocks=48, seq_len=96,
              sampling=None):
    tpu_cfg = TpuConfig(
        batch_size=slots, seq_len=seq_len, max_context_length=32,
        dtype="float32", context_encoding_buckets=[16, 32],
        token_generation_buckets=[48, 96],
        is_continuous_batching=True, paged_attention_enabled=paged,
        pa_num_blocks=blocks, pa_block_size=8,
        on_device_sampling_config=sampling,
    )
    config = LlamaInferenceConfig(tpu_cfg,
                                  load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    return app


@pytest.fixture(scope="module")
def app(tiny_llama_hf_config):
    return _make_app(tiny_llama_hf_config)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(1, 256, size=(n,)).astype(np.int32) for n in (12, 19)]


@pytest.fixture(scope="module")
def base_tokens(app, prompts):
    """Reference greedy tokens from the STEP-WISE (scan-chunk) path."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    return [res[r] for r in rids]


def _device_matches_host(runner):
    """The flush-time identities the telemetry carry guarantees, plus the
    megastep-specific one: drained ``megastep_iters`` == the host's
    committed-inner-step counter == stats()["megastep"]["inner_steps"]."""
    assert not runner._inflight, "pipeline must be flushed for exactness"
    s = runner.stats()
    d = s["device"]
    tokens = sum(e["tokens"] for e in runner.telemetry.events
                 if e["event"] == "commit")
    assert d["tokens_total"] == s["tokens_emitted"] == tokens
    kinds = {}
    for rec in runner.telemetry.steps:
        kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
    assert d["steps"] == kinds, (d["steps"], kinds)
    if runner.megastep_k is not None:
        m = s["megastep"]
        assert d["megastep_iters"] == m["inner_steps"]
        # exits (and so "dispatches") cover BOTH while_loop flavors — plain
        # decode megasteps and spec draft-verify megasteps (ISSUE-19); the
        # scanned mixed megastep has no early exit and stays outside
        mega_disp = (d["steps"].get("megastep", 0)
                     + d["steps"].get("spec_megastep", 0))
        assert mega_disp == m["dispatches"]
        assert sum(m["exits"].values()) == m["dispatches"]
    return s, d


@pytest.mark.parametrize("k", [1, 4, 16])
@pytest.mark.parametrize("depth", [1, 2])
def test_megastep_matrix_exactness(app, prompts, base_tokens, k, depth):
    """K x async_depth matrix: bit-identical tokens, exact counters, and the
    megastep actually carried the decode work (no silent step-wise run)."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=k,
                                      async_mode=True, async_depth=depth,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == base_tokens, f"K={k} depth={depth}"
    s, d = _device_matches_host(runner)
    assert d["steps"].get("megastep", 0) > 0
    assert d["steps"].get("decode", 0) == 0   # nothing fell back to the scan


def test_megastep_sync_exactness(app, prompts, base_tokens):
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=8,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == base_tokens
    _device_matches_host(runner)


def test_megastep_mid_loop_eos(app, prompts, base_tokens):
    """A row emitting its eos mid-loop freezes in-graph and the megastep
    early-exits ``stopped`` once every row froze — same tokens as the
    step-wise eos replay, device eos counter exact."""
    eos = int(base_tokens[0][5])
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      telemetry=True)
    rid = runner.submit(prompts[0], max_new_tokens=12, eos_token_id=eos)
    out = runner.run_to_completion()[rid]
    assert out == base_tokens[0][:6]
    s, d = _device_matches_host(runner)
    assert d["eos"] == 1
    assert s["megastep"]["exits"].get("stopped", 0) >= 1


def test_megastep_ring_wrap_service(app, prompts, base_tokens):
    """megastep_ring < megastep_k: each dispatch runs at most ring inner
    steps, exits ``ring``, the host drains (services) the ring, and the next
    dispatch continues — tokens stay bit-identical."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      megastep_ring=4, telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == base_tokens
    s, _ = _device_matches_host(runner)
    assert s["megastep"]["exits"].get("ring", 0) >= 1
    for rec in runner.telemetry.steps:
        if rec["kind"] == "megastep":
            assert rec["iterations"] <= 4


def test_megastep_block_budget_early_exit(tiny_llama_hf_config, prompts):
    """In-loop block consumption up to the host-pre-reserved budget: with the
    free list drained to one spare block, the megastep reserves what it can,
    early-exits ``blocks`` at the coverage edge, and continues next dispatch
    once blocks free up — tokens identical to the unconstrained run, and the
    zero-progress preemption path never fires."""
    app = _make_app(tiny_llama_hf_config)
    max_new = 40
    ref = ContinuousBatchingRunner(app, decode_chunk=4)
    ref_ids = [ref.submit(p, max_new_tokens=max_new) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=max_new) for p in prompts]
    runner.step()                   # place both prompts + first full megastep
    # squeeze the free list down to ONE spare block (a filler "prompt" holds
    # the rest) so the next best-effort reservation comes up short of K
    bs = runner.block_size
    n_hold = runner.allocator.num_free - 1
    assert n_hold > 0
    filler = np.arange(1000, 1000 + n_hold * bs - 1).astype(np.int32) % 251
    held, _ = runner.allocator.allocate_for_prompt(filler)
    assert runner.allocator.num_free == 1
    runner.step()                   # partial coverage -> in-graph blocks exit
    s = runner.stats()
    assert s["megastep"]["exits"].get("blocks", 0) >= 1, s["megastep"]
    runner.allocator.free_sequence(held)     # release pressure; continue
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    _device_matches_host(runner)
    assert runner.num_preemptions == 0


def test_megastep_arrival_flag_early_exit(app, prompts, base_tokens):
    """Queued work that cannot place sets the in-graph service flag: the
    megastep yields after ONE inner step (insert latency bounded by the
    service condition, not by K) and the queued request's tokens still land
    bit-identically."""
    long_new = 12
    # reference: step-wise serving of 3 requests through 2 slots
    ref = ContinuousBatchingRunner(app, decode_chunk=4)
    ref_ids = [ref.submit(p, max_new_tokens=long_new)
               for p in [*prompts, prompts[0]]]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=long_new)
            for p in [*prompts, prompts[0]]]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    s, _ = _device_matches_host(runner)
    assert s["megastep"]["exits"].get("arrival", 0) >= 1


def test_megastep_sampled_exactness_aligned(tiny_llama_hf_config, prompts):
    """Sampled serving: with the megastep's inner-step count aligned to the
    step-wise chunk (K == ring == decode_chunk, no early exit in the
    window), the in-graph key schedule is identical and sampled tokens stay
    BIT-exact — the strongest available sampled-path equivalence (unaligned
    groupings legitimately consume different keys)."""
    sampling = OnDeviceSamplingConfig(do_sample=True, top_k=8,
                                      temperature=0.8)
    app = _make_app(tiny_llama_hf_config, sampling=sampling)
    ref = ContinuousBatchingRunner(app, decode_chunk=8)
    rids = [ref.submit(p, max_new_tokens=16) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=8, megastep_k=8,
                                      telemetry=True)
    rids2 = [runner.submit(p, max_new_tokens=16) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids2] == [ref_out[r] for r in rids]
    _device_matches_host(runner)


def test_megastep_spec_composition(tiny_llama_hf_config, app, prompts):
    """Spec serving + megastep: away from the seq boundary the chunks run as
    device spec megasteps (ISSUE-19); near the boundary the ONE guarded
    seq-room fall-through runs plain decode megasteps — both visible in the
    counters, tokens identical to the same spec config without any of it."""
    draft_hf = dict(tiny_llama_hf_config, hidden_size=32,
                    intermediate_size=64, num_hidden_layers=1,
                    num_attention_heads=2, num_key_value_heads=2)
    draft = _make_app(draft_hf)
    max_new = 84                      # drives the row into the seq_len-K band
    ref = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                   spec_chunk=2)
    rid = ref.submit(prompts[0], max_new_tokens=max_new)
    ref_out = ref.run_to_completion()[rid]
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=4,
                                      telemetry=True)
    rid2 = runner.submit(prompts[0], max_new_tokens=max_new)
    out = runner.run_to_completion()[rid2]
    assert out == ref_out
    s, d = _device_matches_host(runner)
    assert d["steps"].get("spec_megastep", 0) > 0
    assert d["steps"].get("megastep", 0) > 0
    ft = runner.telemetry.registry.get(
        "serving_fallthrough_total",
        labels={"from": "spec", "reason": "seq_room"})
    assert ft is not None and ft.value > 0


def test_megastep_mixed_fall_through_recorded(tiny_llama_hf_config, prompts):
    """Mixed scheduler + megastep: the ONE guarded fall-through runs the
    megastep, counts the reason, and stamps it on the very next megastep
    step-timeline record — a degraded mixed run is visible, never silent."""
    app = _make_app(tiny_llama_hf_config)
    ref = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                   prefill_token_budget=32,
                                   mixed_decode_steps=2)
    rids = [ref.submit(p, max_new_tokens=8) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                      prefill_token_budget=32,
                                      mixed_decode_steps=2, megastep_k=4,
                                      telemetry=True)
    rids2 = [runner.submit(p, max_new_tokens=8) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids2] == [ref_out[r] for r in rids]
    s, d = _device_matches_host(runner)
    assert d["steps"].get("mixed", 0) > 0
    assert d["steps"].get("megastep", 0) > 0
    stamped = [rec for rec in runner.telemetry.steps
               if rec["kind"] == "megastep" and "fall_through" in rec]
    assert stamped and stamped[0]["fall_through"].startswith("mixed:")
    c = runner.telemetry.registry.get(
        "serving_fallthrough_total",
        labels={"from": "mixed", "reason": "no_insert_in_flight"})
    assert c is not None and c.value > 0


# ---------------------------------------------------------------- ISSUE-19 --
# megastep-everything: the while_loop spec draft-verify megastep and the
# scanned mixed insert+decode megastep must stay BIT-IDENTICAL to their
# step-wise references, with every degradation visible (fall-through
# counters, exit reasons), never silent.
@pytest.fixture(scope="module")
def draft(tiny_llama_hf_config):
    draft_hf = dict(tiny_llama_hf_config, hidden_size=32,
                    intermediate_size=64, num_hidden_layers=1,
                    num_attention_heads=2, num_key_value_heads=2)
    return _make_app(draft_hf)


@pytest.fixture(scope="module")
def spec_base(app, draft, prompts):
    """Step-wise spec reference: tokens + the acceptance histogram the
    megastep must reproduce exactly (same iteration math, same commit)."""
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    return ([res[r] for r in rids], runner.acceptance_counts.tolist())


@pytest.mark.parametrize("k", [1, 4, 16])
def test_spec_megastep_matrix_exactness(app, draft, prompts, spec_base, k):
    """megastep_k sweep: bit-identical tokens AND acceptance histogram vs the
    step-wise spec path, all chunks carried by the while_loop (no silent
    step-wise spec_chunk dispatch), device counters exact."""
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=k,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == spec_base[0], f"K={k}"
    assert runner.acceptance_counts.tolist() == spec_base[1]
    s, d = _device_matches_host(runner)
    assert d["steps"].get("spec_megastep", 0) > 0
    assert d["steps"].get("spec_chunk", 0) == 0
    assert d["steps"].get("decode", 0) == 0


def test_spec_megastep_mid_chunk_eos(app, draft, prompts, spec_base):
    """An eos landing mid-window stops the row via the in-graph commit_row
    replay: truncated tokens identical to step-wise, ``stopped`` exit."""
    eos = int(spec_base[0][0][5])
    ref = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                   spec_chunk=2)
    rid = ref.submit(prompts[0], max_new_tokens=12, eos_token_id=eos)
    want = ref.run_to_completion()[rid]
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=8,
                                      telemetry=True)
    rid2 = runner.submit(prompts[0], max_new_tokens=12, eos_token_id=eos)
    out = runner.run_to_completion()[rid2]
    assert out == want
    s, d = _device_matches_host(runner)
    assert d["eos"] == 1
    assert s["megastep"]["exits"].get("stopped", 0) >= 1


def test_spec_megastep_ring_wrap_service(app, draft, prompts, spec_base):
    """megastep_ring < megastep_k: the acceptance ring fills, the loop exits
    ``ring``, the host drains the ring and re-dispatches — bit-identical."""
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=16,
                                      megastep_ring=2, telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == spec_base[0]
    s, _ = _device_matches_host(runner)
    assert s["megastep"]["exits"].get("ring", 0) >= 1
    for rec in runner.telemetry.steps:
        if rec["kind"] == "spec_megastep":
            assert rec["iterations"] <= 2


def test_spec_megastep_block_coverage_exit_resume(tiny_llama_hf_config,
                                                  prompts, draft):
    """Preempt-free pressure handling INSIDE the loop: with the free list
    squeezed, the best-effort reservation covers fewer than K windows, the
    loop exits ``blocks`` at the coverage edge, and serving resumes exactly
    once blocks free up — tokens identical to the unconstrained reference."""
    app = _make_app(tiny_llama_hf_config)
    # small K (16-token reservations per dispatch) + a long run: later
    # dispatches must re-reserve under pressure instead of coasting on the
    # first dispatch's headroom
    max_new = 64
    ref = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                   spec_chunk=2)
    ref_ids = [ref.submit(p, max_new_tokens=max_new) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=4,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=max_new) for p in prompts]
    runner.step()                  # place prompts + first spec megastep
    bs = runner.block_size
    n_hold = runner.allocator.num_free - 1
    assert n_hold > 0
    filler = np.arange(1000, 1000 + n_hold * bs - 1).astype(np.int32) % 251
    held, _ = runner.allocator.allocate_for_prompt(filler)
    assert runner.allocator.num_free == 1
    # dispatches under pressure coast on the previous reservation's headroom
    # first, then hit the coverage edge -> in-graph ``blocks`` exit
    for _ in range(8):
        runner.step()
        if runner.stats()["megastep"]["exits"].get("blocks", 0):
            break
    s = runner.stats()
    assert s["megastep"]["exits"].get("blocks", 0) >= 1, s["megastep"]
    runner.allocator.free_sequence(held)
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    _device_matches_host(runner)
    assert runner.num_preemptions == 0


def test_spec_megastep_arrival_service(app, draft, prompts, spec_base):
    """Queued work that cannot place sets the in-graph service flag: the spec
    megastep yields after ONE window so insert latency is bounded by the
    chunk, and the queued request's tokens still land bit-identically."""
    ref = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                   spec_chunk=2)
    ref_ids = [ref.submit(p, max_new_tokens=12)
               for p in [*prompts, prompts[0]]]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=16,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12)
            for p in [*prompts, prompts[0]]]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    s, _ = _device_matches_host(runner)
    assert s["megastep"]["exits"].get("arrival", 0) >= 1


def test_spec_megastep_eagle_fall_through(tiny_llama_hf_config, app, prompts):
    """Eagle spec + megastep_k: the eagle chunk threads hidden-state
    re-injection the while_loop carry does not model — the guarded
    fall-through counts the reason and serves step-wise, bit-identically."""
    from neuronx_distributed_inference_tpu.models import eagle as eagle_lib
    from neuronx_distributed_inference_tpu.runtime.eagle import (
        draft_args_from_target)

    import jax

    d_args = draft_args_from_target(app.arch_args, num_layers=1)
    d_params = eagle_lib.init_eagle_params(
        d_args, jax.random.PRNGKey(3), dtype=app.tpu_config.jax_dtype,
        inv_freq=app.inv_freq_from_config(app.config))
    ref = ContinuousBatchingRunner(app, eagle_draft=(d_args, d_params),
                                   speculation_length=3)
    rid = ref.submit(prompts[0], max_new_tokens=12)
    want = ref.run_to_completion()[rid]
    runner = ContinuousBatchingRunner(app, eagle_draft=(d_args, d_params),
                                      speculation_length=3, megastep_k=4,
                                      telemetry=True)
    rid2 = runner.submit(prompts[0], max_new_tokens=12)
    out = runner.run_to_completion()[rid2]
    assert out == want
    ft = runner.telemetry.registry.get(
        "serving_fallthrough_total",
        labels={"from": "spec_mega", "reason": "eagle"})
    assert ft is not None and ft.value > 0


@pytest.fixture(scope="module")
def mixed_prompts():
    """A >chunk prompt: the multi-window plan needs >= 2 insert windows in
    flight (a 40-token prompt under prefill_chunk=16 gives three)."""
    rng = np.random.default_rng(7)
    return [rng.integers(1, 256, size=(n,)).astype(np.int32)
            for n in (12, 40)]


@pytest.fixture(scope="module")
def mixed_base(app, mixed_prompts):
    """Step-wise mixed (chunked-prefill) reference tokens."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16)
    rids = [runner.submit(p, max_new_tokens=12) for p in mixed_prompts]
    res = runner.run_to_completion()
    return [res[r] for r in rids]


@pytest.mark.parametrize("k", [4, 16])
def test_mixed_megastep_exactness(app, mixed_prompts, mixed_base, k):
    """Multi-window mixed megastep: whole insert windows + decode steps
    batched into one scanned dispatch, tokens bit-identical to the step-wise
    mixed scheduler, and the scan actually carried windows (mixed_megastep
    steps in the device carry)."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                      megastep_k=k, telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in mixed_prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == mixed_base, f"K={k}"
    s, d = _device_matches_host(runner)
    assert d["steps"].get("mixed_megastep", 0) > 0


def test_mixed_megastep_pending_arrival_fall_through(app, mixed_prompts,
                                                     mixed_base):
    """A queued request at dispatch time falls through visibly (the megastep
    cannot admit mid-scan) and the step-wise path serves it — tokens
    bit-identical to the fully step-wise reference."""
    ref = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16)
    ref_ids = [ref.submit(p, max_new_tokens=12)
               for p in [*mixed_prompts, mixed_prompts[0]]]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                      megastep_k=4, telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12)
            for p in [*mixed_prompts, mixed_prompts[0]]]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    ft = runner.telemetry.registry.get(
        "serving_fallthrough_total",
        labels={"from": "mixed_mega", "reason": "pending_arrival"})
    assert ft is not None and ft.value > 0
    _device_matches_host(runner)


def test_megastep_validation(tiny_llama_hf_config, app):
    dense = _make_app(tiny_llama_hf_config, paged=False)
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatchingRunner(dense, megastep_k=4)
    with pytest.raises(ValueError, match="megastep_k must be"):
        ContinuousBatchingRunner(app, megastep_k=0)
    with pytest.raises(ValueError, match="megastep_ring must be"):
        ContinuousBatchingRunner(app, megastep_k=4, megastep_ring=0)
    with pytest.raises(ValueError, match="megastep_ring requires"):
        ContinuousBatchingRunner(app, megastep_ring=4)
