"""Device-resident serving megasteps (ISSUE-10 / ROADMAP open item 2): the
``lax.while_loop`` serving loop must produce BIT-IDENTICAL tokens to the
step-wise path across the whole exactness matrix — K in {1, 4, 16} x
async_depth in {1, 2}, including mid-loop eos, in-loop block consumption up
to the host-pre-reserved budget followed by a ``blocks`` early-exit, the
emitted-ring wrap service exit, the pending-arrival service flag, and
spec-chunk / mixed-step composition through the ONE guarded fall-through —
while the device telemetry carry's per-inner-step counters keep matching the
host's event-log recompute exactly at every pipeline flush.
"""

import numpy as np
import pytest

from neuronx_distributed_inference_tpu.config import (
    OnDeviceSamplingConfig, TpuConfig, load_pretrained_config)
from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)
from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
    ContinuousBatchingRunner)


def _make_app(hf_cfg, paged=True, slots=2, blocks=48, seq_len=96,
              sampling=None):
    tpu_cfg = TpuConfig(
        batch_size=slots, seq_len=seq_len, max_context_length=32,
        dtype="float32", context_encoding_buckets=[16, 32],
        token_generation_buckets=[48, 96],
        is_continuous_batching=True, paged_attention_enabled=paged,
        pa_num_blocks=blocks, pa_block_size=8,
        on_device_sampling_config=sampling,
    )
    config = LlamaInferenceConfig(tpu_cfg,
                                  load_config=load_pretrained_config(hf_cfg))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    return app


@pytest.fixture(scope="module")
def app(tiny_llama_hf_config):
    return _make_app(tiny_llama_hf_config)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(1, 256, size=(n,)).astype(np.int32) for n in (12, 19)]


@pytest.fixture(scope="module")
def base_tokens(app, prompts):
    """Reference greedy tokens from the STEP-WISE (scan-chunk) path."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    return [res[r] for r in rids]


def _device_matches_host(runner):
    """The flush-time identities the telemetry carry guarantees, plus the
    megastep-specific one: drained ``megastep_iters`` == the host's
    committed-inner-step counter == stats()["megastep"]["inner_steps"]."""
    assert not runner._inflight, "pipeline must be flushed for exactness"
    s = runner.stats()
    d = s["device"]
    tokens = sum(e["tokens"] for e in runner.telemetry.events
                 if e["event"] == "commit")
    assert d["tokens_total"] == s["tokens_emitted"] == tokens
    kinds = {}
    for rec in runner.telemetry.steps:
        kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
    assert d["steps"] == kinds, (d["steps"], kinds)
    if runner.megastep_k is not None:
        m = s["megastep"]
        assert d["megastep_iters"] == m["inner_steps"]
        assert d["steps"].get("megastep", 0) == m["dispatches"]
        assert sum(m["exits"].values()) == m["dispatches"]
    return s, d


@pytest.mark.parametrize("k", [1, 4, 16])
@pytest.mark.parametrize("depth", [1, 2])
def test_megastep_matrix_exactness(app, prompts, base_tokens, k, depth):
    """K x async_depth matrix: bit-identical tokens, exact counters, and the
    megastep actually carried the decode work (no silent step-wise run)."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=k,
                                      async_mode=True, async_depth=depth,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == base_tokens, f"K={k} depth={depth}"
    s, d = _device_matches_host(runner)
    assert d["steps"].get("megastep", 0) > 0
    assert d["steps"].get("decode", 0) == 0   # nothing fell back to the scan


def test_megastep_sync_exactness(app, prompts, base_tokens):
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=8,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == base_tokens
    _device_matches_host(runner)


def test_megastep_mid_loop_eos(app, prompts, base_tokens):
    """A row emitting its eos mid-loop freezes in-graph and the megastep
    early-exits ``stopped`` once every row froze — same tokens as the
    step-wise eos replay, device eos counter exact."""
    eos = int(base_tokens[0][5])
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      telemetry=True)
    rid = runner.submit(prompts[0], max_new_tokens=12, eos_token_id=eos)
    out = runner.run_to_completion()[rid]
    assert out == base_tokens[0][:6]
    s, d = _device_matches_host(runner)
    assert d["eos"] == 1
    assert s["megastep"]["exits"].get("stopped", 0) >= 1


def test_megastep_ring_wrap_service(app, prompts, base_tokens):
    """megastep_ring < megastep_k: each dispatch runs at most ring inner
    steps, exits ``ring``, the host drains (services) the ring, and the next
    dispatch continues — tokens stay bit-identical."""
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      megastep_ring=4, telemetry=True)
    rids = [runner.submit(p, max_new_tokens=12) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == base_tokens
    s, _ = _device_matches_host(runner)
    assert s["megastep"]["exits"].get("ring", 0) >= 1
    for rec in runner.telemetry.steps:
        if rec["kind"] == "megastep":
            assert rec["iterations"] <= 4


def test_megastep_block_budget_early_exit(tiny_llama_hf_config, prompts):
    """In-loop block consumption up to the host-pre-reserved budget: with the
    free list drained to one spare block, the megastep reserves what it can,
    early-exits ``blocks`` at the coverage edge, and continues next dispatch
    once blocks free up — tokens identical to the unconstrained run, and the
    zero-progress preemption path never fires."""
    app = _make_app(tiny_llama_hf_config)
    max_new = 40
    ref = ContinuousBatchingRunner(app, decode_chunk=4)
    ref_ids = [ref.submit(p, max_new_tokens=max_new) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=max_new) for p in prompts]
    runner.step()                   # place both prompts + first full megastep
    # squeeze the free list down to ONE spare block (a filler "prompt" holds
    # the rest) so the next best-effort reservation comes up short of K
    bs = runner.block_size
    n_hold = runner.allocator.num_free - 1
    assert n_hold > 0
    filler = np.arange(1000, 1000 + n_hold * bs - 1).astype(np.int32) % 251
    held, _ = runner.allocator.allocate_for_prompt(filler)
    assert runner.allocator.num_free == 1
    runner.step()                   # partial coverage -> in-graph blocks exit
    s = runner.stats()
    assert s["megastep"]["exits"].get("blocks", 0) >= 1, s["megastep"]
    runner.allocator.free_sequence(held)     # release pressure; continue
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    _device_matches_host(runner)
    assert runner.num_preemptions == 0


def test_megastep_arrival_flag_early_exit(app, prompts, base_tokens):
    """Queued work that cannot place sets the in-graph service flag: the
    megastep yields after ONE inner step (insert latency bounded by the
    service condition, not by K) and the queued request's tokens still land
    bit-identically."""
    long_new = 12
    # reference: step-wise serving of 3 requests through 2 slots
    ref = ContinuousBatchingRunner(app, decode_chunk=4)
    ref_ids = [ref.submit(p, max_new_tokens=long_new)
               for p in [*prompts, prompts[0]]]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, megastep_k=16,
                                      telemetry=True)
    rids = [runner.submit(p, max_new_tokens=long_new)
            for p in [*prompts, prompts[0]]]
    res = runner.run_to_completion()
    assert [res[r] for r in rids] == [ref_out[r] for r in ref_ids]
    s, _ = _device_matches_host(runner)
    assert s["megastep"]["exits"].get("arrival", 0) >= 1


def test_megastep_sampled_exactness_aligned(tiny_llama_hf_config, prompts):
    """Sampled serving: with the megastep's inner-step count aligned to the
    step-wise chunk (K == ring == decode_chunk, no early exit in the
    window), the in-graph key schedule is identical and sampled tokens stay
    BIT-exact — the strongest available sampled-path equivalence (unaligned
    groupings legitimately consume different keys)."""
    sampling = OnDeviceSamplingConfig(do_sample=True, top_k=8,
                                      temperature=0.8)
    app = _make_app(tiny_llama_hf_config, sampling=sampling)
    ref = ContinuousBatchingRunner(app, decode_chunk=8)
    rids = [ref.submit(p, max_new_tokens=16) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=8, megastep_k=8,
                                      telemetry=True)
    rids2 = [runner.submit(p, max_new_tokens=16) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids2] == [ref_out[r] for r in rids]
    _device_matches_host(runner)


def test_megastep_spec_composition(tiny_llama_hf_config, app, prompts):
    """Spec serving + megastep: the near-boundary plain fall-through runs
    device megasteps (visible in the fall-through counter and the device
    step counts), tokens identical to the same spec config without it."""
    draft_hf = dict(tiny_llama_hf_config, hidden_size=32,
                    intermediate_size=64, num_hidden_layers=1,
                    num_attention_heads=2, num_key_value_heads=2)
    draft = _make_app(draft_hf)
    max_new = 84                      # drives the row into the seq_len-K band
    ref = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                   spec_chunk=2)
    rid = ref.submit(prompts[0], max_new_tokens=max_new)
    ref_out = ref.run_to_completion()[rid]
    runner = ContinuousBatchingRunner(app, draft=draft, speculation_length=4,
                                      spec_chunk=2, megastep_k=4,
                                      telemetry=True)
    rid2 = runner.submit(prompts[0], max_new_tokens=max_new)
    out = runner.run_to_completion()[rid2]
    assert out == ref_out
    s, d = _device_matches_host(runner)
    assert d["steps"].get("spec_chunk", 0) > 0
    assert d["steps"].get("megastep", 0) > 0
    ft = runner.telemetry.registry.get(
        "serving_fallthrough_total",
        labels={"from": "spec", "reason": "seq_room"})
    assert ft is not None and ft.value > 0


def test_megastep_mixed_fall_through_recorded(tiny_llama_hf_config, prompts):
    """Mixed scheduler + megastep: the ONE guarded fall-through runs the
    megastep, counts the reason, and stamps it on the very next megastep
    step-timeline record — a degraded mixed run is visible, never silent."""
    app = _make_app(tiny_llama_hf_config)
    ref = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                   prefill_token_budget=32,
                                   mixed_decode_steps=2)
    rids = [ref.submit(p, max_new_tokens=8) for p in prompts]
    ref_out = ref.run_to_completion()
    runner = ContinuousBatchingRunner(app, decode_chunk=4, prefill_chunk=16,
                                      prefill_token_budget=32,
                                      mixed_decode_steps=2, megastep_k=4,
                                      telemetry=True)
    rids2 = [runner.submit(p, max_new_tokens=8) for p in prompts]
    res = runner.run_to_completion()
    assert [res[r] for r in rids2] == [ref_out[r] for r in rids]
    s, d = _device_matches_host(runner)
    assert d["steps"].get("mixed", 0) > 0
    assert d["steps"].get("megastep", 0) > 0
    stamped = [rec for rec in runner.telemetry.steps
               if rec["kind"] == "megastep" and "fall_through" in rec]
    assert stamped and stamped[0]["fall_through"].startswith("mixed:")
    c = runner.telemetry.registry.get(
        "serving_fallthrough_total",
        labels={"from": "mixed", "reason": "no_insert_in_flight"})
    assert c is not None and c.value > 0


def test_megastep_validation(tiny_llama_hf_config, app):
    dense = _make_app(tiny_llama_hf_config, paged=False)
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatchingRunner(dense, megastep_k=4)
    with pytest.raises(ValueError, match="megastep_k must be"):
        ContinuousBatchingRunner(app, megastep_k=0)
    with pytest.raises(ValueError, match="megastep_ring must be"):
        ContinuousBatchingRunner(app, megastep_k=4, megastep_ring=0)
    with pytest.raises(ValueError, match="megastep_ring requires"):
        ContinuousBatchingRunner(app, megastep_ring=4)
