"""KV cache semantics tests (≈ reference `test/unit/modules/kvcache/test_kv_cache_manager.py`)."""

import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.modules import kvcache


def _spec(**kw):
    defaults = dict(num_layers=2, batch_size=2, num_kv_heads=2, max_seq_len=16,
                    head_dim=4, dtype=jnp.float32)
    defaults.update(kw)
    return kvcache.KVCacheSpec(**defaults)


def test_init_shapes_and_bytes():
    spec = _spec()
    cache = kvcache.init_cache(spec)
    assert cache["k"].shape == (2, 2, 2, 16, 4)
    assert kvcache.cache_bytes(spec) == 2 * 2 * 2 * 2 * 16 * 4 * 4


def test_prefill_write_and_bucket_read():
    spec = _spec()
    cache = kvcache.init_cache(spec)
    new = jnp.asarray(np.random.randn(2, 2, 8, 4).astype(np.float32))
    layer = kvcache.write_prefill(cache["k"][0], new)
    np.testing.assert_array_equal(np.asarray(layer[:, :, :8]), np.asarray(new))
    np.testing.assert_array_equal(np.asarray(layer[:, :, 8:]), 0)
    sliced = kvcache.read_bucket(layer, 8)
    assert sliced.shape == (2, 2, 8, 4)


def test_decode_write_per_sequence_positions():
    spec = _spec()
    layer = kvcache.init_cache(spec)["k"][0]
    new = jnp.asarray(np.arange(2 * 2 * 1 * 4, dtype=np.float32).reshape(2, 2, 1, 4))
    positions = jnp.asarray(np.array([3, 7], dtype=np.int32))
    out = np.array(kvcache.write_decode(layer, new, positions))
    np.testing.assert_array_equal(out[0, :, 3], np.asarray(new)[0, :, 0])
    np.testing.assert_array_equal(out[1, :, 7], np.asarray(new)[1, :, 0])
    out[0, :, 3] = 0
    out[1, :, 7] = 0
    np.testing.assert_array_equal(out, 0)


def test_decode_write_multi_token():
    spec = _spec()
    layer = kvcache.init_cache(spec)["k"][0]
    new = jnp.asarray(np.random.randn(2, 2, 3, 4).astype(np.float32))
    positions = jnp.asarray(np.array([2, 5], dtype=np.int32))
    out = np.asarray(kvcache.write_decode(layer, new, positions))
    np.testing.assert_array_equal(out[0, :, 2:5], np.asarray(new)[0])
    np.testing.assert_array_equal(out[1, :, 5:8], np.asarray(new)[1])


def test_batched_gather_reorders_sequences():
    spec = _spec()
    cache = kvcache.init_cache(spec)
    cache = {k: v.at[:, 0].set(1.0).at[:, 1].set(2.0) for k, v in cache.items()}
    swapped = kvcache.batched_gather(cache, jnp.asarray([1, 0]))
    np.testing.assert_array_equal(np.asarray(swapped["k"][:, 0]), 2.0)
    np.testing.assert_array_equal(np.asarray(swapped["k"][:, 1]), 1.0)


def test_fp8_cache_writes_saturate_outliers():
    """Values past the fp8 range must SATURATE at every cache-write path, not
    overflow to NaN (e4m3fn) / Inf (e5m2) — the kernels' fast fp8 decode
    assumes finite payloads, so an overflow would surface as silently wrong
    logits rather than NaN."""
    import jax.numpy as jnp
    import ml_dtypes

    from neuronx_distributed_inference_tpu.modules import kvcache
    from neuronx_distributed_inference_tpu.modules.block_kvcache import (
        write_slots)

    for dt in (jnp.float8_e4m3fn, jnp.float8_e5m2):
        fmax = float(ml_dtypes.finfo(dt).max)
        x = jnp.array([10 * fmax, -10 * fmax, 3.5, 0.0], jnp.float32)
        out = np.asarray(kvcache.to_cache_dtype(x, dt)).astype(np.float32)
        assert np.isfinite(out).all(), dt
        assert out[0] == fmax and out[1] == -fmax

    # through the dense prefill write
    cache = jnp.zeros((2, 2, 8, 4), jnp.float8_e4m3fn)
    new = jnp.full((2, 2, 3, 4), 1e6, jnp.float32)
    written = np.asarray(kvcache.write_prefill(cache, new)).astype(np.float32)
    assert np.isfinite(written).all()

    # through the paged slot write
    pool = jnp.zeros((4, 2, 8, 4), jnp.float8_e4m3fn)
    newp = jnp.full((1, 2, 2, 4), -1e6, jnp.float32)
    slots = jnp.array([[0, 1]], jnp.int32)
    writtenp = np.asarray(write_slots(pool, newp, slots)).astype(np.float32)
    assert np.isfinite(writtenp).all()
