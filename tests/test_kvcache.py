"""KV cache semantics tests (≈ reference `test/unit/modules/kvcache/test_kv_cache_manager.py`)."""

import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.modules import kvcache


def _spec(**kw):
    defaults = dict(num_layers=2, batch_size=2, num_kv_heads=2, max_seq_len=16,
                    head_dim=4, dtype=jnp.float32)
    defaults.update(kw)
    return kvcache.KVCacheSpec(**defaults)


def test_init_shapes_and_bytes():
    spec = _spec()
    cache = kvcache.init_cache(spec)
    assert cache["k"].shape == (2, 2, 2, 16, 4)
    assert kvcache.cache_bytes(spec) == 2 * 2 * 2 * 2 * 16 * 4 * 4


def test_prefill_write_and_bucket_read():
    spec = _spec()
    cache = kvcache.init_cache(spec)
    new = jnp.asarray(np.random.randn(2, 2, 8, 4).astype(np.float32))
    layer = kvcache.write_prefill(cache["k"][0], new)
    np.testing.assert_array_equal(np.asarray(layer[:, :, :8]), np.asarray(new))
    np.testing.assert_array_equal(np.asarray(layer[:, :, 8:]), 0)
    sliced = kvcache.read_bucket(layer, 8)
    assert sliced.shape == (2, 2, 8, 4)


def test_decode_write_per_sequence_positions():
    spec = _spec()
    layer = kvcache.init_cache(spec)["k"][0]
    new = jnp.asarray(np.arange(2 * 2 * 1 * 4, dtype=np.float32).reshape(2, 2, 1, 4))
    positions = jnp.asarray(np.array([3, 7], dtype=np.int32))
    out = np.array(kvcache.write_decode(layer, new, positions))
    np.testing.assert_array_equal(out[0, :, 3], np.asarray(new)[0, :, 0])
    np.testing.assert_array_equal(out[1, :, 7], np.asarray(new)[1, :, 0])
    out[0, :, 3] = 0
    out[1, :, 7] = 0
    np.testing.assert_array_equal(out, 0)


def test_decode_write_multi_token():
    spec = _spec()
    layer = kvcache.init_cache(spec)["k"][0]
    new = jnp.asarray(np.random.randn(2, 2, 3, 4).astype(np.float32))
    positions = jnp.asarray(np.array([2, 5], dtype=np.int32))
    out = np.asarray(kvcache.write_decode(layer, new, positions))
    np.testing.assert_array_equal(out[0, :, 2:5], np.asarray(new)[0])
    np.testing.assert_array_equal(out[1, :, 5:8], np.asarray(new)[1])


def test_batched_gather_reorders_sequences():
    spec = _spec()
    cache = kvcache.init_cache(spec)
    cache = {k: v.at[:, 0].set(1.0).at[:, 1].set(2.0) for k, v in cache.items()}
    swapped = kvcache.batched_gather(cache, jnp.asarray([1, 0]))
    np.testing.assert_array_equal(np.asarray(swapped["k"][:, 0]), 2.0)
    np.testing.assert_array_equal(np.asarray(swapped["k"][:, 1]), 1.0)
