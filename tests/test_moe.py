"""MoE: routing semantics, HF parity for Mixtral / Qwen3-MoE, EP sharding.

≈ reference MoE tests (`test/integration/tiny_model/features/test_moe_ep.py`,
`test/unit/models/*` state-dict conversions).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from neuronx_distributed_inference_tpu.config import TpuConfig, load_pretrained_config
from neuronx_distributed_inference_tpu.ops.moe import MoEArgs, route



pytestmark = pytest.mark.slow  # heavy e2e: excluded from the fast gate

def _tpu_cfg(**kw):
    base = dict(batch_size=2, seq_len=64, max_context_length=32, dtype="float32",
                context_encoding_buckets=[16, 32], token_generation_buckets=[32, 64])
    base.update(kw)
    return TpuConfig(**base)


def test_route_topk_sparsity_and_renorm():
    moe = MoEArgs(num_experts=8, experts_per_tok=2, norm_topk_prob=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    gates = np.asarray(route(w, x, moe))
    assert gates.shape == (5, 8)
    assert ((gates > 0).sum(axis=1) == 2).all()
    np.testing.assert_allclose(gates.sum(axis=1), 1.0, atol=1e-6)

    moe_raw = MoEArgs(num_experts=8, experts_per_tok=2, norm_topk_prob=False)
    gates_raw = np.asarray(route(w, x, moe_raw))
    assert (gates_raw.sum(axis=1) < 1.0).all()   # softmax mass of just top-2


def _mixtral_pair():
    from transformers import MixtralConfig, MixtralForCausalLM as HFMixtral

    from neuronx_distributed_inference_tpu.models.mixtral import MixtralForCausalLM

    cfg = MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=512,
        num_local_experts=4, num_experts_per_tok=2, rope_theta=10000.0,
        tie_word_embeddings=False, sliding_window=None)
    torch.manual_seed(0)
    return MixtralForCausalLM, HFMixtral(cfg).eval(), cfg


def _qwen3_moe_pair():
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM as HFQwen3Moe

    from neuronx_distributed_inference_tpu.models.qwen3_moe import Qwen3MoeForCausalLM

    cfg = Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=512,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[], rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    return Qwen3MoeForCausalLM, HFQwen3Moe(cfg).eval(), cfg


def _load(app_cls, hf_model, hf_cfg, tpu_cfg):
    config = app_cls.get_config_cls()(
        tpu_cfg, load_config=load_pretrained_config(hf_cfg.to_dict()))
    app = app_cls(None, config)
    state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    app._put_params(app.convert_hf_state_dict(state, app.config))
    return app


@pytest.mark.parametrize("pair_fn", [_mixtral_pair, _qwen3_moe_pair])
def test_moe_parity_vs_hf(pair_fn):
    app_cls, hf, cfg = pair_fn()
    app = _load(app_cls, hf, cfg, _tpu_cfg())

    rng = np.random.default_rng(0)
    input_ids = rng.integers(1, 256, size=(2, 12)).astype(np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.tensor(input_ids)).logits[:, -1].numpy()
    out = app.generate(input_ids, max_new_tokens=1, return_logits=True)
    np.testing.assert_allclose(out.logits[0], hf_logits, atol=5e-4, rtol=1e-3)

    with torch.no_grad():
        hf_out = hf.generate(torch.tensor(input_ids), max_new_tokens=8,
                             do_sample=False, pad_token_id=0)
    out = app.generate(input_ids, max_new_tokens=8)
    np.testing.assert_array_equal(out.tokens, hf_out[:, 12:].numpy())


def test_moe_expert_parallel_matches_single_device():
    """ep=4 over the virtual CPU mesh must produce the same logits as ep=1
    (≈ reference EP logit-matching, `test_moe_ep.py`)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    app_cls, hf, cfg = _mixtral_pair()
    app1 = _load(app_cls, hf, cfg, _tpu_cfg())
    app4 = _load(app_cls, hf, cfg, _tpu_cfg(ep_degree=4))

    rng = np.random.default_rng(1)
    input_ids = rng.integers(1, 256, size=(2, 10)).astype(np.int64)
    out1 = app1.generate(input_ids, max_new_tokens=4, return_logits=True)
    out4 = app4.generate(input_ids, max_new_tokens=4, return_logits=True)
    np.testing.assert_array_equal(out1.tokens, out4.tokens)
    np.testing.assert_allclose(out1.logits[0], out4.logits[0], atol=2e-4, rtol=1e-3)


def test_moe_tensor_parallel_matches_single_device():
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    app_cls, hf, cfg = _qwen3_moe_pair()
    app1 = _load(app_cls, hf, cfg, _tpu_cfg())
    app2 = _load(app_cls, hf, cfg, _tpu_cfg(tp_degree=2))

    rng = np.random.default_rng(2)
    input_ids = rng.integers(1, 256, size=(2, 10)).astype(np.int64)
    out1 = app1.generate(input_ids, max_new_tokens=4)
    out2 = app2.generate(input_ids, max_new_tokens=4)
    np.testing.assert_array_equal(out1.tokens, out2.tokens)


@pytest.mark.parametrize("mode", ["tp", "ep_tp", None])
def test_moe_hybrid_decode_sharding_matches_default(mode):
    """Hybrid MoE sharding (≈ reference CTE-vs-TKG TP/EP groups + dispatch
    options, `models/config.py:1055-1061,602`): remapping the DECODE graph's
    expert-activation axes must not change a single token or logit — GSPMD
    just derives different dispatch/combine collectives per graph."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from neuronx_distributed_inference_tpu.config import MoEHybridShardingConfig

    app_cls, hf, cfg = _mixtral_pair()
    base = _load(app_cls, hf, cfg, _tpu_cfg(tp_degree=2, ep_degree=4))
    hybrid = _load(app_cls, hf, cfg, _tpu_cfg(
        tp_degree=2, ep_degree=4,
        moe_hybrid_sharding=MoEHybridShardingConfig(
            decode_experts=mode,
            decode_expert_mlp="ep" if mode == "tp" else None)))

    rng = np.random.default_rng(3)
    input_ids = rng.integers(1, 256, size=(2, 10)).astype(np.int64)
    out_b = base.generate(input_ids, max_new_tokens=4, return_logits=True)
    out_h = hybrid.generate(input_ids, max_new_tokens=4, return_logits=True)
    np.testing.assert_array_equal(out_b.tokens, out_h.tokens)
    for lb, lh in zip(out_b.logits, out_h.logits):
        np.testing.assert_allclose(lh, lb, atol=2e-4, rtol=1e-3)
