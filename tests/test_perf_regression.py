"""Perf-regression canaries (≈ reference perf thresholds,
`test/integration/tp32/models/llama/llama3.1/8b/test_llama3_1_8b_4layer_dtype.py:31-54`).

Real wall-clock thresholds only mean something on TPU hardware (the driver's bench
covers that), so CI guards the *compiled program's* memory traffic instead:
XLA's cost analysis of a decode step bounds "bytes accessed", which is exactly what
regressed in round 1 (scan cache-slice copies + a serialized KV write tripled the
decode step's traffic without any test noticing).

The canary MECHANICS now live in ``analysis/canaries.py`` on the graph-contract
auditor: each group is (AuditUnits at a pinned geometry) + (cross-unit budget
Rules), measured once by ``analysis.auditor.audit`` — one framework, shared with
``scripts/audit_graphs.py --canaries``, instead of per-test ad-hoc
``cost_analysis`` plumbing. The tests below keep their historical names as thin
wrappers over named rules so history stays comparable; each also inherits the
generic contract checks (aliasing, host-sync freedom, dtype discipline) on its
units for free.
"""

import functools

import jax
import pytest

from neuronx_distributed_inference_tpu.analysis import canaries
from neuronx_distributed_inference_tpu.analysis.auditor import audit

HF = canaries.CANARY_HF


@functools.lru_cache(maxsize=None)
def _group_report(name):
    """Audit one canary group once per session; wrappers read its findings."""
    units, rules = canaries.canary_group(name)
    return audit(units, rules)


@pytest.fixture(scope="module", autouse=True)
def _drop_canary_fleets():
    """Reports are plain data; the cached canary apps/runners (params +
    block pools per variant) must not stay resident for the rest of the
    pytest session once this module's wrappers have their reports."""
    yield
    canaries.clear_caches()


def _assert_rules(report, *rule_names):
    """The whole group audit holds (units + rules), and each named rule both
    ran and passed — a rule that silently vanishes is itself a failure."""
    assert report.ok, "\n".join(
        f"{f.unit}: [{f.check}] {f.status} {f.detail}"
        for f in report.violations())
    for name in rule_names:
        statuses = [f.status for f in report.findings
                    if f.unit == name and f.check == "rule"]
        assert statuses == ["pass"], (name, statuses, report.findings)


def test_decode_step_bytes_bounded():
    """Per-step traffic must stay within 3x of the ideal working set.

    Ideal = params once + KV bucket read + small activations. The jnp path pays
    the known scan cache-movement taxes (~2.6x today — the reason the Pallas
    stacked-cache path exists); the bound fails if anything pushes it further.
    (Wrapper: ``dense_decode`` canary group.)"""
    _assert_rules(_group_report("dense_decode"), "dense_decode_bytes_bounded")


def test_kernel_decode_not_more_traffic():
    """The Pallas stacked-cache path must not regress vs the jnp path's bound.

    (XLA cannot see inside pallas custom-calls, so this bounds the surrounding
    graph: no hidden cache copies at the kernel boundaries.)"""
    _assert_rules(_group_report("dense_decode"), "kernel_decode_not_more_traffic")


@pytest.mark.skipif(jax.default_backend() == "cpu",
                    reason="wall-clock thresholds need accelerator hardware")
def test_decode_step_wall_clock():
    """On real hardware: a tiny-model decode step stays under a generous bound
    (catches order-of-magnitude regressions without flaking on noise).

    Wall-clock is a runtime property, not a graph property — this one stays
    off the auditor by design."""
    import time

    import numpy as np

    from neuronx_distributed_inference_tpu.config import (
        TpuConfig, load_pretrained_config)
    from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
        LlamaForCausalLM, LlamaInferenceConfig)

    cfg = TpuConfig(batch_size=8, seq_len=512, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512])
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 256, size=(8, 16)).astype(np.int32)
    app.generate(ids, max_new_tokens=64)
    out = app.generate(ids, max_new_tokens=64, collect_latency=True)
    s = sum(x for x, _ in out.decode_latencies_s)
    n = sum(x for _, x in out.decode_latencies_s)
    assert (s / n) * 1000 < 20.0, f"{s/n*1000:.2f} ms/step for a 4-layer tiny model"


def test_fused_paged_decode_bytes_one_kv_pass_and_table_invariant():
    """The ISSUE-4 canaries for the FUSED append+attend hot path.

    (a) Table-width invariance: like the separate attend, the fused kernel's
        compiled traffic must not scale with the block-table width (reads
        track live length through the in-kernel DMA loop bound).
    (b) ~ONE KV pass: the fused kernel takes the pool ONCE per layer (one
        aliased in/out operand pair) — the separate path charges it at every
        write (in+out) AND once per attend cell operand (kb*bb copies), plus
        the real read-after-write of the appended block. Compiled
        bytes-accessed must therefore sit within 2x of the aliased
        pool-in+out accounting (L layers x (k+v) x (in+out)), and far below
        the separate path's charge (measured ~9x at this geometry).
    (Wrapper: ``fused_paged`` canary group.)"""
    _assert_rules(_group_report("fused_paged"), "fused_table_invariant",
                  "fused_vs_separate", "fused_one_kv_pass")


def test_paged_kernel_bytes_invariant_to_table_width():
    """The ragged paged kernel's compiled traffic must NOT scale with the block-table
    width — that is the entire point (reads track live length, not table width; the
    gather path grows with the table, ~1.3x from MB=4 to MB=32 even on this tiny
    model). Absolute bytes are NOT comparable between the two paths: XLA charges a
    pallas custom call's operands (the whole block pool) conservatively, while the
    kernel's real DMA traffic is the indexed blocks only — so the canary is the
    scaling, not the level. (Wrapper: ``paged_table_width`` canary group.)"""
    _assert_rules(_group_report("paged_table_width"),
                  "paged_kernel_table_invariant",
                  "paged_gather_grows_with_table")


def test_multiquery_paged_attend_bytes_invariant_to_table_width():
    """The q_len>1 (speculative verify) paged kernel path must keep the
    compiled traffic INVARIANT to the block-table width, exactly like the
    q_len=1 canary above — the multi-query attend streams each row's live
    blocks once for all K queries. The gather fallback grows with the table
    (and re-streams it per query), which is the cliff the kernel exists to
    avoid. (Wrapper: ``multiquery`` canary group.)"""
    _assert_rules(_group_report("multiquery"), "mq_kernel_table_invariant",
                  "mq_gather_grows_with_table")


@pytest.mark.parametrize("t", [64, 128, 256])
def test_mixed_chunk_attend_never_falls_back_to_gather(t):
    """The ISSUE-2 canary: the mixed-step chunked attend at q_len 64/128/256
    must ride the Pallas variable-q_len kernel — compiled traffic INVARIANT to
    the block-table width. A silent fallback to the gather path would scale
    with the table (it materializes the full (B, MB*BS) KV view per layer),
    which is exactly the regression this canary pins.

    Widths 16 vs 32: below 16 blocks the kernel's per-cell block count (and
    so its conservative XLA operand accounting) is table-bound rather than
    VMEM-budget-bound, so the canary compares two widths where the cell
    geometry is fixed and only the table grows. (Wrapper: ``mixed_chunk``
    canary group — audited once, asserted per chunk length.)"""
    _assert_rules(_group_report("mixed_chunk"),
                  f"mixed_kernel_table_invariant_t{t}")


def test_mixed_chunk_gather_fallback_grows_with_table():
    """Documents the cliff the mixed kernel avoids: the gather path's chunk
    attend traffic grows with the block-table width."""
    _assert_rules(_group_report("mixed_chunk"),
                  "mixed_gather_grows_with_table")


def test_megastep_one_executable_bytes_k_invariant():
    """The ISSUE-10 canary: the device-resident serving megastep is ONE
    executable whose compiled HBM traffic is ~K-invariant — weights and KV
    pools are passed (and charged) ONCE however many inner steps the
    lax.while_loop runs. The inner-step count is a DYNAMIC operand (no
    executable sweep across seq-room clamps at all); the only K-shaped
    static is the emitted-token ring capacity, and a 4x ring sweep must move
    compiled bytes by <2% (measured: identical). The absolute rule bounds
    the whole dispatch at 16x one weights+pool pass — the tripwire against
    an extra O(pool) copy sneaking into the loop body. (Wrapper:
    ``megastep`` canary group.)"""
    _assert_rules(_group_report("megastep"),
                  "megastep_bytes_k_invariant", "megastep_one_weights_pass")


def test_amla_rescale_zero_extra_hbm():
    """The ISSUE-19 leg a canary: AMLA exponent-add rescaling is compute-only
    — toggling TPUINF_AMLA must leave the compiled decode-step traffic
    byte-identical in both directions (0.1% bound). An AMLA variant that
    spills rescale scratch to HBM trips this immediately. (Wrapper: ``amla``
    canary group.)"""
    _assert_rules(_group_report("amla"),
                  "amla_zero_extra_hbm", "amla_zero_hbm_savings")


def test_lenpar_split_bytes_invariant_one_kv_pass():
    """The ISSUE-19 leg b canary: the in-path KV-length split re-shards the
    same block walk across grid rows, so engaging it (bs=1, 32-wide table —
    a 4-way auto split) must not move compiled bytes by more than 2% vs the
    TPUINF_LENPAR=0 control, and the split step stays within the fused
    one-KV-pass absolute budget. (Wrapper: ``lenpar`` canary group.)"""
    _assert_rules(_group_report("lenpar"),
                  "lenpar_split_byte_invariant", "lenpar_one_kv_pass")


def test_spec_megastep_one_executable_bytes_k_invariant():
    """The ISSUE-19 leg c canary: the SPECULATIVE serving megastep is ONE
    executable — a 4x emitted-acceptance ring sweep (the only K-shaped
    static) must move compiled bytes by <2%, and the whole dispatch stays
    within 32x one (target+draft) weights+pools pass. (Wrapper:
    ``spec_megastep`` canary group.)"""
    _assert_rules(_group_report("spec_megastep"),
                  "spec_megastep_bytes_k_invariant",
                  "spec_megastep_one_weights_pass")


def test_tp_decode_collective_schedule_pinned():
    """The PR-5 multichip canary: the tp>1 decode step's collective schedule
    is pinned per layer and its ICI bytes are table/batch-shape-invariant.

    The layer stack runs under lax.scan, so the optimized HLO carries the
    per-layer collective schedule exactly once — a refactor that reintroduces
    a stray all-gather (or any per-layer collective) changes the multiset
    immediately. Invariance: block-table width and slot count must not leak
    into the schedule (reads track live state; collectives move activations,
    never table-shaped buffers). The overlap path must carry ring
    collective-permutes; the GSPMD fallback none. (Wrapper:
    ``tp_collectives`` canary group.)"""
    _assert_rules(_group_report("tp_collectives"),
                  "tp_schedule_table_invariant", "tp_schedule_batch_invariant",
                  "tp_schedule_pinned", "tp_fallback_no_ring")


def test_moe_ep_decode_collective_schedule_pinned():
    """The ISSUE-16 expert-dispatch canary: the ep>1 MoE paged decode step's
    collective schedule is pinned and table/batch-shape-invariant. The
    overlap path (parallel/overlap.expert_ring_moe) must carry the
    expert-ring collective-permutes whose transfers hide behind the local
    expert matmuls; the TPUINF_EP_OVERLAP=0 fallback keeps the GSPMD-placed
    combine all-reduce and no permutes — bit-exactness between the two is
    pinned by tests/test_moe_serving.py. (Wrapper: ``moe_ep_collectives``
    canary group.)"""
    _assert_rules(_group_report("moe_ep_collectives"),
                  "moe_ep_schedule_table_invariant",
                  "moe_ep_schedule_batch_invariant",
                  "moe_ep_schedule_pinned", "moe_ep_fallback_no_ring")


def test_disabled_telemetry_adds_no_measurable_step_overhead():
    """The ISSUE-3 canary: the serving loop's telemetry hooks
    (step_start / annotate / step_record / note_emitted — exactly the calls
    _step_plain makes per step) must be free when telemetry is disabled.

    Measured as a guarded RELATIVE bound: an instrumented loop over a
    stand-in step workload vs the same loop without the hooks. The workload
    (~a few tens of µs of numpy) is orders of magnitude SMALLER than a real
    jitted decode dispatch (~ms), so a 25% bound here corresponds to a
    sub-percent bound on the real step; the best-of-repeats guard plus an
    absolute per-step-delta escape hatch (r12: a contended CI box inflates
    the µs-scale bare loop itself, which flaked the purely-relative gate)
    keeps scheduler noise from flaking the gate while still catching real
    work sneaking onto the disabled path. (Host-side runtime property —
    stays off the graph auditor by design.)"""
    import time

    import numpy as np

    from neuronx_distributed_inference_tpu.utils.metrics import (
        ServingTelemetry)

    tel = ServingTelemetry(enabled=False)
    a = np.random.default_rng(0).standard_normal((96, 96))
    emitted = {i: [1, 2, 3, 4] for i in range(8)}

    def bare(n):
        acc = 0.0
        for _ in range(n):
            acc += float((a @ a)[0, 0])
        return acc

    def instrumented(n):
        acc = 0.0
        for _ in range(n):
            t0 = tel.step_start()
            with tel.annotate("decode"):
                acc += float((a @ a)[0, 0])
            tel.step_record(t0, "decode", iterations=4, tokens=32,
                            occupancy=8, slots=8, kv_free=40, kv_total=48)
            tel.note_emitted(emitted)
        return acc

    n = 300
    bare(n), instrumented(n)                      # warm caches / allocator
    best = []
    for fn in (bare, instrumented):
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            fn(n)
            times.append(time.perf_counter() - t0)
        best.append(min(times))
    t_bare, t_inst = best
    per_step_delta = (t_inst - t_bare) / n
    assert t_inst < t_bare * 1.25 or per_step_delta < 100e-6, (
        f"disabled-telemetry hooks cost {(t_inst / t_bare - 1) * 100:.1f}% / "
        f"{per_step_delta * 1e6:.0f} µs per step on a µs-scale stand-in "
        f"(bare {t_bare * 1e3:.2f} ms, "
        f"instrumented {t_inst * 1e3:.2f} ms for {n} steps)")


def test_tracing_off_path_adds_no_per_observation_overhead():
    """The ISSUE-12 canary beside the two above: request tracing is post-hoc
    span building, so the LIVE serving path gains only (a) the
    ``exemplar=None`` default on histogram observes and (b) the trace-id
    mint at arrival — and the mint must not run at all when telemetry is
    disabled. Pinned as an absolute per-call ceiling on the no-exemplar
    observe (generous vs a ~ms dispatch; catches accidental per-observe
    exemplar/dict work sneaking onto the default path) plus the
    disabled-path allocation check."""
    import time

    from neuronx_distributed_inference_tpu.utils.metrics import (
        MetricsRegistry, ServingTelemetry)

    # (b) disabled telemetry mints nothing — arrival stays allocation-free
    tel = ServingTelemetry(enabled=False)
    for rid in range(100):
        tel.request_arrival(rid, prompt_len=16, max_new_tokens=64)
    assert tel._trace_seq == 0 and tel.requests == {}

    # (a) the no-exemplar observe: best-of-repeats absolute per-call bound
    h = MetricsRegistry().histogram("t_seconds")
    h.observe(0.01)                                  # warm
    n = 2000
    best = min(_timed(lambda: [h.observe(0.01) for _ in range(n)])
               for _ in range(5))
    per_call = best / n
    assert per_call < 50e-6, (
        f"no-exemplar Histogram.observe costs {per_call * 1e6:.1f} µs/call "
        f"— exemplar work leaked onto the tracing-off path")
    assert h.exemplars is None, "observe() without exemplar allocated storage"


def _timed(fn):
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def test_enabled_telemetry_with_carry_drain_stays_microseconds_per_step():
    """The ISSUE-7 extension of the canary above: the ENABLED path — per-step
    record building, note_emitted lifecycle folding, flight-ring append, AND
    the device-carry drain (to_dict of the fetched counter block) — must stay
    O(100 µs)/step. Two-sided guard: the relative bound vs the same µs-scale
    stand-in workload catches creep on an idle box, and the ABSOLUTE
    per-step-delta ceiling keeps a contended CI box (where the µs-scale bare
    loop itself inflates) from flaking the gate while still catching the
    real failure modes — a per-step device sync (~ms over the tunnel) or
    per-step spooling of the full event log. Either bound passing is
    acceptance: both are far under 1% of a real ~100 ms decode-chunk
    dispatch (bench.py's ``telemetry_overhead_ratio`` measures the same
    property on the real serving loop)."""
    import time

    import numpy as np

    from neuronx_distributed_inference_tpu.utils import (
        device_telemetry as dtel)
    from neuronx_distributed_inference_tpu.utils.metrics import (
        ServingTelemetry)

    tel = ServingTelemetry()                       # ENABLED, flight ring on
    a = np.random.default_rng(0).standard_normal((96, 96))
    emitted = {i: [1, 2, 3, 4] for i in range(8)}
    for rid in emitted:
        tel.request_arrival(rid, prompt_len=16, max_new_tokens=64)
        tel.request_placed(rid, slot=rid)
    carry = np.zeros((dtel.CARRY_LEN,), np.int32)  # a drained (host) block

    def bare(n):
        acc = 0.0
        for _ in range(n):
            acc += float((a @ a)[0, 0])
        return acc

    def instrumented(n):
        acc = 0.0
        for _ in range(n):
            t0 = tel.step_start()
            with tel.annotate("decode"):
                acc += float((a @ a)[0, 0])
            tel.step_record(t0, "decode", iterations=4, tokens=32,
                            occupancy=8, slots=8, kv_free=40, kv_total=48)
            tel.note_emitted(emitted)
            tel.note_device_counters(dtel.to_dict(carry))
        return acc

    n = 300
    bare(n), instrumented(n)                      # warm caches / allocator
    best = []
    for fn in (bare, instrumented):
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            fn(n)
            times.append(time.perf_counter() - t0)
        best.append(min(times))
    t_bare, t_inst = best
    per_step_delta = (t_inst - t_bare) / n
    assert t_inst < t_bare * 4.0 or per_step_delta < 800e-6, (
        f"enabled-telemetry + carry-drain hooks cost "
        f"{(t_inst / t_bare - 1) * 100:.1f}% / "
        f"{per_step_delta * 1e6:.0f} µs per step on a µs-scale stand-in "
        f"(bare {t_bare * 1e3:.2f} ms, instrumented {t_inst * 1e3:.2f} ms "
        f"for {n} steps)")


def test_roofline_plumbing_adds_no_overhead_off_the_profiled_path():
    """The ISSUE-14 canary beside the three above: the roofline
    measured-vs-model join runs ONLY inside attribute_device_time (an
    explicit profiling window). Off that path the plumbing is one None
    attribute on the telemetry (read by snapshot()) — no model build, no
    AOT lowering, no provenance probe (whose git subprocess would be
    milliseconds), pinned as an absolute per-call ceiling on the
    snapshot-side read plus the structural no-state checks
    (tests/test_perf_model.py pins the runner-level half: serving steps
    with telemetry disabled leave runner._perf_model None)."""
    import sys
    import time

    from neuronx_distributed_inference_tpu.utils.metrics import (
        ServingTelemetry)

    tel = ServingTelemetry(enabled=False)
    assert tel.roofline is None
    # the off-path read: snapshot()["roofline"] must be a plain attribute
    # carry-through (no computation, no model import side effects)
    n = 500
    tel.snapshot()                                   # warm
    best = min(_timed(lambda: [tel.snapshot() for _ in range(n)])
               for _ in range(3))
    per_call = best / n
    assert per_call < 2e-3, (
        f"disabled-telemetry snapshot() costs {per_call * 1e6:.0f} µs/call "
        f"— roofline/provenance work leaked onto the read path")
    # structural: nothing on this path imported/probed provenance state
    # (fingerprint caching is module-level; a probe would have populated it)
    prov_mod = sys.modules.get(
        "neuronx_distributed_inference_tpu.utils.provenance")
    if prov_mod is not None:
        t0 = time.perf_counter()
        prov_mod.fingerprint()                        # cached after first use
        assert time.perf_counter() - t0 < 0.5
