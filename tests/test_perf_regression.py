"""Perf-regression canaries (≈ reference perf thresholds,
`test/integration/tp32/models/llama/llama3.1/8b/test_llama3_1_8b_4layer_dtype.py:31-54`).

Real wall-clock thresholds only mean something on TPU hardware (the driver's bench
covers that), so CI guards the *compiled program's* memory traffic instead:
XLA's cost analysis of a decode step bounds "bytes accessed", which is exactly what
regressed in round 1 (scan cache-slice copies + a serialized KV write tripled the
decode step's traffic without any test noticing).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_inference_tpu.config import TpuConfig, load_pretrained_config
from neuronx_distributed_inference_tpu.models.llama.modeling_llama import (
    LlamaForCausalLM, LlamaInferenceConfig)


HF = {
    "model_type": "llama", "vocab_size": 256, "hidden_size": 256,
    "intermediate_size": 512, "num_hidden_layers": 4, "num_attention_heads": 2,
    "num_key_value_heads": 2, "max_position_embeddings": 1024,
    "rms_norm_eps": 1e-5, "rope_theta": 10000.0, "tie_word_embeddings": False,
}


def _bytes_accessed(lowered):
    """bytes-accessed from a lowered computation, across jax versions
    (cost_analysis() returns a dict on current jax, a one-element list of
    dicts on older releases)."""
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost["bytes accessed"])


def _app(kernel):
    cfg = TpuConfig(batch_size=8, seq_len=512, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    return app


def _decode_bytes(app, steps=4):
    """Compiled bytes-accessed of one decode chunk, normalized per step."""
    from neuronx_distributed_inference_tpu.ops import sampling as sampling_ops

    app.reset_cache()
    b = app.tpu_config.max_batch_size
    sp = sampling_ops.prepare_sampling_params(b)
    lowered = app._decode_step.lower(
        app.params, jnp.zeros((b,), jnp.int32), np.full((b,), 128, np.int32),
        app.kv_cache, sp, jax.random.PRNGKey(0), decode_bucket=512,
        num_steps=steps, with_logits=False, greedy=True)
    return _bytes_accessed(lowered) / steps


def test_decode_step_bytes_bounded():
    """Per-step traffic must stay within 3x of the ideal working set.

    Ideal = params once + KV bucket read + small activations. The jnp path pays
    the known scan cache-movement taxes (~2.6x today — the reason the Pallas
    stacked-cache path exists); the bound fails if anything pushes it further."""
    app = _app(kernel=False)
    per_step = _decode_bytes(app)
    params_bytes = sum(x.nbytes for x in jax.tree.leaves(app.params))
    cache_bytes = sum(x.nbytes for x in jax.tree.leaves(app.kv_cache))
    ideal = params_bytes + cache_bytes          # one pass over weights + cache
    assert per_step < 3.0 * ideal, (per_step, ideal)


def test_kernel_decode_not_more_traffic():
    """The Pallas stacked-cache path must not regress vs the jnp path's bound.

    (XLA cannot see inside pallas custom-calls, so this bounds the surrounding
    graph: no hidden cache copies at the kernel boundaries.)"""
    per_step_kernel = _decode_bytes(_app(kernel=True))
    per_step_jnp = _decode_bytes(_app(kernel=False))
    assert per_step_kernel < per_step_jnp * 1.1, (per_step_kernel, per_step_jnp)


@pytest.mark.skipif(jax.default_backend() == "cpu",
                    reason="wall-clock thresholds need accelerator hardware")
def test_decode_step_wall_clock():
    """On real hardware: a tiny-model decode step stays under a generous bound
    (catches order-of-magnitude regressions without flaking on noise)."""
    import time

    app = _app(kernel=None)
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 256, size=(8, 16)).astype(np.int32)
    app.generate(ids, max_new_tokens=64)
    out = app.generate(ids, max_new_tokens=64, collect_latency=True)
    s = sum(x for x, _ in out.decode_latencies_s)
    n = sum(x for _, x in out.decode_latencies_s)
    assert (s / n) * 1000 < 20.0, f"{s/n*1000:.2f} ms/step for a 4-layer tiny model"


def _paged_decode_bytes(kernel, mb, steps=4, fused=True):
    """Compiled bytes-accessed of one paged-CB decode chunk at block-table width
    ``mb``, normalized per step. ``fused`` toggles the fused append+attend
    kernel vs the separate write-then-attend kernels (trace-time env)."""
    import os

    from neuronx_distributed_inference_tpu.ops import sampling as sampling_ops
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    cfg = TpuConfig(batch_size=8, seq_len=4096, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=66, pa_block_size=128,
                    decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    r = ContinuousBatchingRunner(app, decode_chunk=steps)
    b = 8
    sp = sampling_ops.prepare_sampling_params(b)
    prev = os.environ.get("TPUINF_PAGED_FUSED")
    os.environ["TPUINF_PAGED_FUSED"] = "1" if fused else "0"
    try:
        lowered = r._decode_step.lower(
            app.params, jnp.zeros((b,), jnp.int32),
            jnp.full((b,), 128, jnp.int32), jnp.ones((b,), bool),
            jnp.full((b,), 64, jnp.int32), r.cache,
            jnp.zeros((b, mb), jnp.int32), jnp.zeros((b, steps), jnp.int32),
            sp, jax.random.PRNGKey(0), jnp.zeros((b,), jnp.int32),
            jnp.full((b,), -1, jnp.int32), num_steps=steps)
    finally:
        if prev is None:
            os.environ.pop("TPUINF_PAGED_FUSED", None)
        else:
            os.environ["TPUINF_PAGED_FUSED"] = prev
    return _bytes_accessed(lowered) / steps


def test_fused_paged_decode_bytes_one_kv_pass_and_table_invariant():
    """The ISSUE-4 canaries for the FUSED append+attend hot path.

    (a) Table-width invariance: like the separate attend, the fused kernel's
        compiled traffic must not scale with the block-table width (reads
        track live length through the in-kernel DMA loop bound).
    (b) ~ONE KV pass: the fused kernel takes the pool ONCE per layer (one
        aliased in/out operand pair) — the separate path charges it at every
        write (in+out) AND once per attend cell operand (kb*bb copies), plus
        the real read-after-write of the appended block. Compiled
        bytes-accessed must therefore sit within 2x of the aliased
        pool-in+out accounting (L layers x (k+v) x (in+out)), and far below
        the separate path's charge (measured ~9x at this geometry)."""
    fused_4 = _paged_decode_bytes(True, 4, fused=True)
    fused_32 = _paged_decode_bytes(True, 32, fused=True)
    assert fused_32 <= fused_4 * 1.02, (fused_4, fused_32)

    sep_4 = _paged_decode_bytes(True, 4, fused=False)
    assert fused_4 <= 0.25 * sep_4, (fused_4, sep_4)

    # one-KV-pass bound: L x (k+v) x (in + out) pool charges, 2x slack for
    # params/activations/logits in the surrounding graph
    cfg_pool = 66 * 128 * 2 * 128 * 2            # blocks x BS x Hkv x D x bf16
    l_layers = HF["num_hidden_layers"]
    pass_bytes = l_layers * 2 * 2 * cfg_pool
    assert fused_4 <= 2.0 * pass_bytes, (fused_4, pass_bytes)


def test_paged_kernel_bytes_invariant_to_table_width():
    """The ragged paged kernel's compiled traffic must NOT scale with the block-table
    width — that is the entire point (reads track live length, not table width; the
    gather path grows with the table, ~1.3x from MB=4 to MB=32 even on this tiny
    model). Absolute bytes are NOT comparable between the two paths: XLA charges a
    pallas custom call's operands (the whole block pool) conservatively, while the
    kernel's real DMA traffic is the indexed blocks only — so the canary is the
    scaling, not the level."""
    kern_4 = _paged_decode_bytes(True, 4)
    kern_32 = _paged_decode_bytes(True, 32)
    assert kern_32 <= kern_4 * 1.02, (kern_4, kern_32)
    gather_4 = _paged_decode_bytes(None, 4)
    gather_32 = _paged_decode_bytes(None, 32)
    assert gather_32 > gather_4 * 1.15, (gather_4, gather_32)   # documents the cliff


def _multiquery_paged_bytes(kernel, mb, t=4):
    """Compiled bytes-accessed of one MULTI-QUERY (q_len=t) paged decode — the
    speculative verify shape — at block-table width ``mb``."""
    from neuronx_distributed_inference_tpu.models import base as model_base

    cfg = TpuConfig(batch_size=8, seq_len=4096, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=66, pa_block_size=128,
                    decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    cache = app.make_paged_cache(cfg.pa_num_blocks, cfg.pa_block_size)
    b = 8
    use_kernel = bool(kernel)

    def _verify(params, ids, positions, cache, bt, sm):
        return model_base.decode_forward(
            params, app.arch_args, ids, positions, cache, None,
            mesh=app.mesh, rules=app.sharding_rules, block_table=bt,
            slot_mapping=sm, use_kernel=use_kernel)

    lowered = jax.jit(_verify, donate_argnums=(3,)).lower(
        app.params, jnp.zeros((b, t), jnp.int32), jnp.full((b,), 128, jnp.int32),
        cache, jnp.zeros((b, mb), jnp.int32), jnp.zeros((b, t), jnp.int32))
    return _bytes_accessed(lowered)


def test_multiquery_paged_attend_bytes_invariant_to_table_width():
    """The q_len>1 (speculative verify) paged kernel path must keep the
    compiled traffic INVARIANT to the block-table width, exactly like the
    q_len=1 canary above — the multi-query attend streams each row's live
    blocks once for all K queries. The gather fallback grows with the table
    (and re-streams it per query), which is the cliff the kernel exists to
    avoid; absolute levels are not comparable between the paths (XLA charges
    a pallas custom call's operands conservatively), so the canary is the
    scaling."""
    kern_4 = _multiquery_paged_bytes(True, 4)
    kern_32 = _multiquery_paged_bytes(True, 32)
    assert kern_32 <= kern_4 * 1.02, (kern_4, kern_32)
    gather_4 = _multiquery_paged_bytes(None, 4)
    gather_32 = _multiquery_paged_bytes(None, 32)
    assert gather_32 > gather_4 * 1.15, (gather_4, gather_32)


def _mixed_chunk_paged_bytes(kernel, mb, t, b=4):
    """Compiled bytes-accessed of one MIXED-STEP chunk attend (per-row q_lens
    at chunk length ``t``, logit_idx sampling gather) at block-table width
    ``mb``."""
    from neuronx_distributed_inference_tpu.models import base as model_base

    cfg = TpuConfig(batch_size=b, seq_len=4096, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=66, pa_block_size=128,
                    decode_kernel_enabled=kernel)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    cache = app.make_paged_cache(cfg.pa_num_blocks, cfg.pa_block_size)
    use_kernel = bool(kernel)

    def _chunk(params, ids, positions, q_lens, cache, bt, sm):
        return model_base.decode_forward(
            params, app.arch_args, ids, positions, cache, None,
            mesh=app.mesh, rules=app.sharding_rules, block_table=bt,
            slot_mapping=sm, use_kernel=use_kernel, q_lens=q_lens,
            logit_idx=q_lens - 1)

    lowered = jax.jit(_chunk, donate_argnums=(4,)).lower(
        app.params, jnp.zeros((b, t), jnp.int32),
        jnp.full((b,), 64, jnp.int32), jnp.full((b,), t, jnp.int32),
        cache, jnp.zeros((b, mb), jnp.int32), jnp.zeros((b, t), jnp.int32))
    return _bytes_accessed(lowered)


@pytest.mark.parametrize("t", [64, 128, 256])
def test_mixed_chunk_attend_never_falls_back_to_gather(t):
    """The ISSUE-2 canary: the mixed-step chunked attend at q_len 64/128/256
    must ride the Pallas variable-q_len kernel — compiled traffic INVARIANT to
    the block-table width. A silent fallback to the gather path would scale
    with the table (it materializes the full (B, MB*BS) KV view per layer),
    which is exactly the regression this canary pins. Gather growth itself is
    documented at t=64 below.

    Widths 16 vs 32: below 16 blocks the kernel's per-cell block count (and
    so its conservative XLA operand accounting — each cell block is a
    separate pallas operand) is table-bound rather than VMEM-budget-bound, so
    the canary compares two widths where the cell geometry is fixed and only
    the table grows."""
    kern_16 = _mixed_chunk_paged_bytes(True, 16, t)
    kern_32 = _mixed_chunk_paged_bytes(True, 32, t)
    assert kern_32 <= kern_16 * 1.02, (kern_16, kern_32)


def test_mixed_chunk_gather_fallback_grows_with_table():
    """Documents the cliff the mixed kernel avoids: the gather path's chunk
    attend traffic grows with the block-table width."""
    gather_4 = _mixed_chunk_paged_bytes(None, 4, 64)
    gather_32 = _mixed_chunk_paged_bytes(None, 32, 64)
    assert gather_32 > gather_4 * 1.15, (gather_4, gather_32)


def _tp_paged_decode_collective_stats(mb, b=8, steps=2, tp=2, sp=True,
                                      overlap=True):
    """Collective schedule (+ output bytes) of the COMPILED tp>1 paged-CB
    decode chunk — the multichip serving hot path — via
    parallel/overlap.collective_stats over the optimized HLO."""
    import os

    from neuronx_distributed_inference_tpu.ops import sampling as sampling_ops
    from neuronx_distributed_inference_tpu.parallel import overlap as overlap_lib
    from neuronx_distributed_inference_tpu.runtime.continuous_batching import (
        ContinuousBatchingRunner)

    cfg = TpuConfig(batch_size=b, seq_len=4096, max_context_length=128,
                    dtype="bfloat16", context_encoding_buckets=[128],
                    token_generation_buckets=[512],
                    is_continuous_batching=True, paged_attention_enabled=True,
                    pa_num_blocks=66, pa_block_size=128, tp_degree=tp,
                    sequence_parallel_enabled=sp)
    config = LlamaInferenceConfig(cfg, load_config=load_pretrained_config(HF))
    app = LlamaForCausalLM(None, config)
    app.load_random(seed=0)
    r = ContinuousBatchingRunner(app, decode_chunk=steps)
    sp_arr = sampling_ops.prepare_sampling_params(b)
    prev = os.environ.get("TPUINF_TP_OVERLAP")
    os.environ["TPUINF_TP_OVERLAP"] = "1" if overlap else "0"
    try:
        lowered = r._decode_step.lower(
            app.params, jnp.zeros((b,), jnp.int32),
            jnp.full((b,), 128, jnp.int32), jnp.ones((b,), bool),
            jnp.full((b,), 64, jnp.int32), r.cache,
            jnp.zeros((b, mb), jnp.int32), jnp.zeros((b, steps), jnp.int32),
            sp_arr, jax.random.PRNGKey(0), jnp.zeros((b,), jnp.int32),
            jnp.full((b,), -1, jnp.int32), num_steps=steps)
        return overlap_lib.compiled_collective_stats(lowered.compile())
    finally:
        if prev is None:
            os.environ.pop("TPUINF_TP_OVERLAP", None)
        else:
            os.environ["TPUINF_TP_OVERLAP"] = prev


def test_tp_decode_collective_schedule_pinned():
    """The PR-5 multichip canary: the tp>1 decode step's collective schedule
    is pinned per layer and its ICI bytes are table/batch-shape-invariant.

    The layer stack runs under lax.scan, so the optimized HLO carries the
    per-layer collective schedule exactly once — a refactor that reintroduces
    a stray all-gather (or any per-layer collective) changes ``counts``
    immediately. Invariance: block-table width and slot count must not leak
    into the schedule (reads track live state; collectives move activations,
    never table-shaped buffers)."""
    s4 = _tp_paged_decode_collective_stats(mb=4)
    s32 = _tp_paged_decode_collective_stats(mb=32)
    assert s4["counts"] == s32["counts"], (s4["counts"], s32["counts"])
    assert s4["bytes"] == s32["bytes"], (s4["bytes"], s32["bytes"])
    # schedule (op mix) is batch-shape-invariant too; bytes scale with rows
    sb4 = _tp_paged_decode_collective_stats(mb=4, b=4)
    assert sb4["counts"] == s4["counts"], (sb4["counts"], s4["counts"])
    # per-layer pin: a small, bounded schedule (ring permutes + the residual
    # halves + sampling merge) — growth here is a reintroduced collective
    assert 0 < s4["count_total"] <= 48, s4
    # the overlap path really is overlap-scheduled: ring collective-permutes
    # present; the GSPMD fallback carries none
    assert s4["counts"].get("collective-permute", 0) > 0, s4
    fb = _tp_paged_decode_collective_stats(mb=4, overlap=False)
    assert fb["counts"].get("collective-permute", 0) == 0, fb


def test_disabled_telemetry_adds_no_measurable_step_overhead():
    """The ISSUE-3 canary: the serving loop's telemetry hooks
    (step_start / annotate / step_record / note_emitted — exactly the calls
    _step_plain makes per step) must be free when telemetry is disabled.

    Measured as a guarded RELATIVE bound: an instrumented loop over a
    stand-in step workload vs the same loop without the hooks. The workload
    (~a few tens of µs of numpy) is orders of magnitude SMALLER than a real
    jitted decode dispatch (~ms), so a 25% bound here corresponds to a
    sub-percent bound on the real step; the best-of-repeats guard keeps
    scheduler noise from flaking the gate."""
    import time

    from neuronx_distributed_inference_tpu.utils.metrics import (
        ServingTelemetry)

    tel = ServingTelemetry(enabled=False)
    a = np.random.default_rng(0).standard_normal((96, 96))
    emitted = {i: [1, 2, 3, 4] for i in range(8)}

    def bare(n):
        acc = 0.0
        for _ in range(n):
            acc += float((a @ a)[0, 0])
        return acc

    def instrumented(n):
        acc = 0.0
        for _ in range(n):
            t0 = tel.step_start()
            with tel.annotate("decode"):
                acc += float((a @ a)[0, 0])
            tel.step_record(t0, "decode", iterations=4, tokens=32,
                            occupancy=8, slots=8, kv_free=40, kv_total=48)
            tel.note_emitted(emitted)
        return acc

    n = 300
    bare(n), instrumented(n)                      # warm caches / allocator
    best = []
    for fn in (bare, instrumented):
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            fn(n)
            times.append(time.perf_counter() - t0)
        best.append(min(times))
    t_bare, t_inst = best
    assert t_inst < t_bare * 1.25, (
        f"disabled-telemetry hooks cost {(t_inst / t_bare - 1) * 100:.1f}% "
        f"on a µs-scale stand-in step (bare {t_bare * 1e3:.2f} ms, "
        f"instrumented {t_inst * 1e3:.2f} ms for {n} steps)")
