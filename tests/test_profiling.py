"""utils/profiling.py helpers on the CPU backend (previously untested).

The trace/annotate/profile_callable flow and the xplane parser behind
``device_time_ms`` all run without accelerator hardware: jax.profiler writes
an xplane dump for CPU executions too, and ``plane_substr=""`` lets the
parser scan the host plane (on TPU the default "tpu" filter selects the
device plane the bench reads).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_inference_tpu.utils import profiling as prof


def test_annotate_is_a_reentrant_context_manager():
    with prof.annotate("outer"):
        with prof.annotate("inner"):
            x = jnp.asarray(1) + 1
    assert int(x) == 2


def test_trace_creates_logdir_and_dump(tmp_path):
    logdir = str(tmp_path / "trace")
    with prof.trace(logdir):
        np.asarray(jax.jit(lambda x: x * 2)(jnp.ones((8,))))
    assert os.path.isdir(logdir)
    dumped = [f for _, _, fs in os.walk(logdir) for f in fs]
    assert dumped, "jax.profiler wrote no trace files"


def test_profile_callable_returns_result_and_positive_time(tmp_path):
    logdir = str(tmp_path / "prof")

    @jax.jit
    def f(x):
        return (x * 3).sum()

    result, per_iter_s = prof.profile_callable(
        f, jnp.ones((16, 16)), logdir=logdir, warmup=1, iters=2)
    assert float(result) == pytest.approx(16 * 16 * 3)
    assert per_iter_s > 0
    assert os.path.isdir(logdir)


def test_device_time_ms_parses_cpu_trace(tmp_path):
    """The xplane parser over a real CPU trace: with the default TPU plane
    filter it returns None on this backend; with plane_substr="" it either
    finds the jitted program's events (a positive duration) or still returns
    None when the runtime labels them differently — both are valid parses,
    an exception is not."""
    logdir = str(tmp_path / "dt")

    @jax.jit
    def named_decode_probe(x):
        return x @ x

    with prof.trace(logdir):
        for _ in range(3):
            np.asarray(named_decode_probe(jnp.ones((64, 64))))

    assert prof.device_time_ms(logdir, "named_decode_probe") is None  # no TPU plane
    any_plane = prof.device_time_ms(logdir, "named_decode_probe",
                                    plane_substr="")
    assert any_plane is None or any_plane > 0
    # an unmatched name is None, not 0.0 (callers distinguish "not found")
    assert prof.device_time_ms(logdir, "no_such_event_name_xyz",
                               plane_substr="") is None


def test_device_time_ms_missing_dir_returns_none(tmp_path):
    assert prof.device_time_ms(str(tmp_path / "nope"), "decode") is None


def test_enable_hlo_dump_is_idempotent(monkeypatch):
    monkeypatch.setenv("XLA_FLAGS", "")
    prof.enable_hlo_dump("/tmp/xla_dump_test")
    once = os.environ["XLA_FLAGS"]
    assert "--xla_dump_to=/tmp/xla_dump_test" in once
    prof.enable_hlo_dump("/tmp/xla_dump_test")
    assert os.environ["XLA_FLAGS"] == once
