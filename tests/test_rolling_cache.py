"""Rolling (window-sized) KV caches for per-layer attention patterns.

Correctness bar (≈ reference per-layer cache sizes,
`modules/kvcache/kv_cache_manager.py:199-237`): sliding layers must allocate only
window-sized cache stacks — at 128k context this is the difference between fitting
and OOM — while HF token parity holds across the rolling boundary (covered by
tests/test_model_hub.py gemma3/gpt-oss, window 8 < generated length).
"""

import numpy as np

from neuronx_distributed_inference_tpu.config import TpuConfig, load_pretrained_config
from neuronx_distributed_inference_tpu.models.gemma3 import Gemma3ForCausalLM
from neuronx_distributed_inference_tpu.modules import kvcache
import pytest



pytestmark = pytest.mark.slow  # heavy e2e: excluded from the fast gate

GEMMA3_CFG = {
    "model_type": "gemma3_text", "vocab_size": 256, "hidden_size": 64,
    "intermediate_size": 128, "num_hidden_layers": 4, "num_attention_heads": 4,
    "num_key_value_heads": 2, "head_dim": 16, "max_position_embeddings": 4096,
    "rope_theta": 1_000_000.0, "rope_local_base_freq": 10_000.0,
    "sliding_window": 16, "sliding_window_pattern": 2,
    "query_pre_attn_scalar": 16, "tie_word_embeddings": True,
}


def _make(seq_len):
    cfg = TpuConfig(batch_size=2, seq_len=seq_len, max_context_length=32,
                    dtype="float32", context_encoding_buckets=[32],
                    token_generation_buckets=[seq_len])
    config = Gemma3ForCausalLM.get_config_cls()(
        cfg, load_config=load_pretrained_config(GEMMA3_CFG))
    app = Gemma3ForCausalLM(None, config)
    app.load_random(seed=0)
    return app


def test_sliding_layers_allocate_window_sized_cache():
    app = _make(seq_len=2048)
    app.reset_cache()
    # pattern=2: layers 0,2 sliding / 1,3 full
    assert app.kv_cache["k"].shape == (2, 2, 2, 2048, 16)          # full layers
    assert app.kv_cache["k_sliding"].shape == (2, 2, 2, 16, 16)    # window-sized
    full_bytes = app.kv_cache["k"].nbytes + app.kv_cache["v"].nbytes
    slide_bytes = (app.kv_cache["k_sliding"].nbytes
                   + app.kv_cache["v_sliding"].nbytes)
    assert slide_bytes * 64 < full_bytes  # 2048 / 16 = 128x smaller per layer


def test_generation_across_rolling_boundary():
    """Decode far past the window: the rolling cache must keep producing the same
    tokens a full-width (degenerate-rolling) run produces."""
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 256, size=(2, 20)).astype(np.int32)
    # window 16 < seq: true rolling
    small = _make(seq_len=64).generate(prompt, max_new_tokens=30).tokens
    # window >= seq via a pattern override is not config-reachable; instead check
    # self-consistency across decode chunk boundaries (chunk 4 vs one big chunk)
    app = _make(seq_len=64)
    app.tpu_config.decode_chunk_size = 4
    chunked = app.generate(prompt, max_new_tokens=30).tokens
    np.testing.assert_array_equal(small, chunked)


def test_write_prefill_rolling_invariant():
    """Slot j holds the row's largest written position ≡ j (mod W)."""
    rng = np.random.default_rng(1)
    w, s = 4, 10
    cache = np.zeros((2, 1, w, 3), dtype=np.float32)
    new = rng.standard_normal((2, 1, s, 3)).astype(np.float32)
    lengths = np.array([7, 2], dtype=np.int32)
    out = np.asarray(kvcache.write_prefill_rolling(
        cache, new, lengths))
    for b, l in enumerate(lengths):
        for j in range(w):
            q = (l - 1) - ((l - 1 - j) % w)
            if q >= 0:
                np.testing.assert_array_equal(out[b, :, j], new[b, :, q])
            else:
                np.testing.assert_array_equal(out[b, :, j], 0.0)


def test_rolling_mask_reconstructs_positions():
    w, window = 4, 4
    pos = np.array([6], dtype=np.int32)
    mask = np.asarray(kvcache.rolling_mask(pos, 1, w, window))[0, 0, 0]
    # slots hold positions: j=0 -> 4, j=1 -> 5, j=2 -> 6, j=3 -> 3 (evicted by
    # window: 3 <= 6-4+... 3 > 6-4=2 -> kept)
    assert mask.tolist() == [True, True, True, True]
    mask = np.asarray(kvcache.rolling_mask(pos, 1, w, 3))[0, 0, 0]
    # window 3: only positions > 3 admitted -> slot 3 (pos 3) drops
    assert mask.tolist() == [True, True, True, False]


def _make_kernel(seq_len, kernel):
    cfg = TpuConfig(batch_size=2, seq_len=seq_len, max_context_length=32,
                    dtype="float32", context_encoding_buckets=[32],
                    token_generation_buckets=[seq_len],
                    decode_kernel_enabled=kernel)
    config = Gemma3ForCausalLM.get_config_cls()(
        cfg, load_config=load_pretrained_config(GEMMA3_CFG))
    app = Gemma3ForCausalLM(None, config)
    app.load_random(seed=0)
    return app


def test_pattern_decode_kernel_matches_jnp_path():
    """VERDICT r3 #7: sliding/full interleaved layers decode through the Pallas
    stacked-cache kernels (rolling write at p mod W, length-aware attend over
    min(p+1, W) slots) and must match the jnp rolling path token-for-token far
    past the rolling boundary (window 16 << 30 generated)."""
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 256, size=(2, 20)).astype(np.int32)
    jnp_path = _make_kernel(64, kernel=False)
    kern_path = _make_kernel(64, kernel=True)
    ref = jnp_path.generate(prompt, max_new_tokens=30, return_logits=True)
    got = kern_path.generate(prompt, max_new_tokens=30, return_logits=True)
    np.testing.assert_array_equal(ref.tokens, got.tokens)
    for i, (a, b) in enumerate(zip(ref.logits, got.logits)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4,
                                   err_msg=f"step {i}")


def test_pattern_decode_kernel_selector_reports_path():
    """The selector must report the kernel path for pattern families now that the
    gate is lifted (explicit True no longer raises; CPU auto stays off)."""
    app = _make_kernel(64, kernel=True)
    assert app._use_decode_kernel() is True
    assert app._use_paged_decode_kernel() is False   # rolling stacks don't page
