"""CLI flag -> config mapping (≈ reference `create_neuron_config` coverage)."""

from neuronx_distributed_inference_tpu.inference_demo import (build_parser,
                                                              create_tpu_config)


def test_flags_map_to_config():
    args = build_parser().parse_args([
        "--model-path", "/tmp/x", "--batch-size", "8", "--seq-len", "256",
        "--tp-degree", "8", "--attention-dp", "--async-mode",
        "--continuous-batching", "--paged-attention", "--pa-num-blocks", "64",
        "--pa-block-size", "16", "--quantize-weights", "int8",
        "--kv-cache-dtype", "float8_e4m3", "--lora-ckpt", "a=/tmp/a",
        "--max-loras", "2", "--do-sample", "--top-k", "50", "--top-p", "0.9",
    ])
    cfg = create_tpu_config(args)
    assert cfg.tp_degree == 8 and cfg.attention_dp_enabled and cfg.async_mode
    assert cfg.is_continuous_batching and cfg.paged_attention_enabled
    assert cfg.pa_num_blocks == 64 and cfg.pa_block_size == 16
    assert cfg.quantization_config.weight_dtype == "int8"
    assert cfg.quantization_config.kv_cache_dtype == "float8_e4m3"
    assert cfg.lora_serving_config.lora_ckpt_paths == {"a": "/tmp/a"}
    assert cfg.on_device_sampling_config.do_sample
    assert cfg.on_device_sampling_config.top_k == 50


def test_lora_flag_requires_name_eq_dir():
    import pytest

    args = build_parser().parse_args(
        ["--model-path", "/tmp/x", "--lora-ckpt", "/tmp/no_name"])
    with pytest.raises(SystemExit):
        create_tpu_config(args)


def test_speculation_config_mapping():
    args = build_parser().parse_args([
        "--model-path", "/tmp/x", "--speculation-length", "4",
        "--draft-model-path", "/tmp/d"])
    cfg = create_tpu_config(args)
    assert cfg.speculation_config.speculation_length == 4
    assert cfg.speculation_config.draft_model_path == "/tmp/d"
